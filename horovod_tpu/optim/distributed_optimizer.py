"""DistributedOptimizer — data-parallel gradient averaging, optax-native.

TPU-native re-design of the reference's optimizer wrappers:
* TF graph mode: ``DistributedOptimizer.compute_gradients`` allreduces each
  gradient (reference horovod/tensorflow/__init__.py:135-225).
* PyTorch: per-parameter grad hooks fire ``allreduce_async_`` during
  backward; ``step()`` synchronizes (reference horovod/torch/__init__.py:86-227).
* Fork extras: ``is_sparse`` top-k mode (:141-151, 202-216) and the
  ``local`` no-communication flag (:115, 158).

On TPU the optimizer lives inside ONE compiled SPMD program, so "hook per
gradient + background fusion" collapses into a gradient transformation:
``DistributedOptimizer(tx)`` returns an ``optax.GradientTransformation``
whose ``update`` all-reduces the gradient pytree over the mesh axis (fused
into ≤ threshold buckets, compression applied) before delegating to ``tx``.
XLA then overlaps those psums with the backward pass the same way Horovod
overlaps NCCL with autograd — but scheduled by the compiler, not a cycle
thread.

Use inside ``shard_map``/``pjit`` over a mesh with the data axis, or via
:func:`make_train_step`, which builds the canonical step function.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from horovod_tpu import basics
from horovod_tpu.basics import AXIS_NAME
from horovod_tpu.ops import collective_ops
from horovod_tpu.ops.collective_ops import Average, Sum, _ReduceOp
from horovod_tpu.ops.compression import Compression, TopKCompressor
from horovod_tpu.utils.compat import shard_map as _shard_map


def allreduce_gradients(
    grads: Any,
    *,
    op: _ReduceOp = Average,
    axis_name=AXIS_NAME,
    compression=Compression.none,
    fusion_threshold_bytes: int | None = None,
    sparse: bool = False,
    sparse_ratio: float = 0.01,
    process_set=None,
) -> Any:
    """All-reduce a gradient pytree over the mesh axis, fused.

    The in-graph analogue of the reference's per-gradient
    ``hvd.allreduce(grad, average=True, compression=...)`` loop
    (tensorflow/__init__.py:183-209), with Tensor Fusion applied
    structurally: leaves are bucketed (same dtype, ≤ threshold bytes) and
    each bucket is ONE psum (operations.cc:1916-1943's merge, compiled).
    """
    leaves, treedef = jax.tree.flatten(grads)
    if sparse and process_set is not None:
        raise ValueError(
            "process_set does not compose with the top-k sparse path; "
            "members-only sparse reduction needs a set-local allgather"
        )
    if sparse:
        topk = TopKCompressor(ratio=sparse_ratio)
        reduced = [
            topk.sparse_allreduce(g, average=op is Average, axis_name=axis_name)
            for g in leaves
        ]
    else:
        reduced = collective_ops.grouped_allreduce(
            leaves,
            op=op,
            axis_name=axis_name,
            compression=compression,
            fusion_threshold_bytes=fusion_threshold_bytes,
            process_set=process_set,
        )
    return jax.tree.unflatten(treedef, reduced)


class _StatefulCompressionState(NamedTuple):
    """Optimizer-state wrapper when a stateful compressor is attached:
    ``comp`` holds residuals / warm-started factors, ``inner`` the wrapped
    optax state."""

    comp: Any
    inner: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: _ReduceOp = Average,
    axis_name=AXIS_NAME,
    compression=Compression.none,
    fusion_threshold_bytes: int | None = None,
    is_sparse: bool = False,
    sparse_ratio: float = 0.01,
    local: bool = False,
    backward_passes_per_step: int = 1,
    process_set=None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-averaged gradients.

    Parity table with the reference wrapper kwargs:

    ================  =========================================================
    reference                         here
    ================  =========================================================
    ``compression``    ``compression=`` (none / fp16 / bf16 / int8)
    ``sparse_as_dense``  not needed — JAX gradients are dense pytrees
    fork ``is_sparse``   ``is_sparse=True`` + ``sparse_ratio`` (top-k path)
    fork ``self.local``  ``local=True`` skips communication entirely
    ``device_dense`` …  owned by XLA (no device staging knobs on TPU)
    ``backward_passes_per_step``  same name: accumulate k local steps, then
                       one fused allreduce + update (optax.MultiSteps around
                       the reducing transform, so the collective only runs
                       on the flush step — reference torch/__init__.py:115)
    ================  =========================================================

    Must run inside SPMD code where ``axis_name`` is bound (shard_map/pjit
    over the hvd mesh) — the analogue of "must run under mpirun".

    ``compression`` may also be a *stateful* compressor implementing the
    ``init(grads_template)`` / ``reduce(grads, state, ...)`` protocol —
    :class:`horovod_tpu.ops.powersgd.PowerSGDCompressor` or
    :class:`~horovod_tpu.ops.powersgd.ErrorFeedback` around topk/int8.  Its
    state (residuals, warm-started factors) rides in the optimizer state.
    """
    from horovod_tpu.ops.powersgd import (
        as_stateful_compressor,
        is_stateful_compressor,
    )

    # local=True never touches the wire, so residuals/factors would be dead
    # gradient-sized state — skip the stateful machinery entirely.
    stateful = is_stateful_compressor(compression) and not local
    if stateful:
        compression = as_stateful_compressor(compression)
        if is_sparse:
            raise ValueError(
                "is_sparse picks the top-k collective; a stateful compressor "
                "already defines its own wire — wrap TopKCompressor in "
                "ErrorFeedback instead of combining the two flags."
            )
        if process_set is not None:
            raise ValueError(
                "process_set does not compose with stateful compressors "
                "(PowerSGD / ErrorFeedback): their collectives run over "
                "the full axis — silent full-world mixing would corrupt "
                "member updates"
            )
        if op not in (Sum, Average):
            raise ValueError(
                f"stateful compressors support op=Sum/Average, not {op}"
            )

    def init_fn(params):
        inner = optimizer.init(params)
        if stateful:
            return _StatefulCompressionState(
                comp=compression.init(params), inner=inner
            )
        return inner

    def update_fn(grads, state, params=None, **extra):
        comp, inner = (state.comp, state.inner) if stateful else (None, state)
        if local:
            reduced = grads
        elif stateful:
            reduced, comp = compression.reduce(
                grads, comp, axis_name=axis_name, average=op is Average
            )
        else:
            reduced = allreduce_gradients(
                grads,
                op=op,
                axis_name=axis_name,
                compression=compression,
                fusion_threshold_bytes=fusion_threshold_bytes,
                sparse=is_sparse,
                sparse_ratio=sparse_ratio,
                process_set=process_set,
            )
        updates, inner = optimizer.update(reduced, inner, params, **extra)
        if stateful:
            return updates, _StatefulCompressionState(comp=comp, inner=inner)
        return updates, inner

    tx = optax.GradientTransformation(init_fn, update_fn)
    if backward_passes_per_step > 1:
        # Accumulation OUTSIDE the reducing transform: k local micro-grads
        # accumulate with no communication, and the allreduce inside
        # update_fn runs once per k steps on the accumulated gradient.
        # use_grad_mean=False: accumulate by SUM, matching the reference's
        # autograd hooks which add into .grad over the k backward passes
        # (torch/__init__.py:115-165) — a ported script keeps its
        # learning-rate behavior.
        return optax.MultiSteps(
            tx, every_k_schedule=backward_passes_per_step,
            use_grad_mean=False,
        ).gradient_transformation()
    return tx


class TrainStepResult(NamedTuple):
    params: Any
    opt_state: Any
    loss: jax.Array


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    *,
    mesh: jax.sharding.Mesh | None = None,
    axis_name: str = AXIS_NAME,
    donate: bool = True,
) -> Callable[..., TrainStepResult]:
    """Build the canonical data-parallel train step, compiled over the mesh.

    ``loss_fn(params, batch) -> scalar`` is the user's per-shard loss;
    ``optimizer`` is typically ``DistributedOptimizer(...)``.  The returned
    function takes ``(params, opt_state, batch)`` where ``batch`` leaves are
    rank-major (dim 0 == world size × local batch) and params/opt_state are
    replicated; it returns updated replicated params, opt_state, and the
    globally-averaged loss.

    This is the whole L5→L2 stack of the reference collapsed into one
    compiled program: examples/tensorflow_mnist.py:85's
    ``opt.minimize(loss)`` → stack §3.2 of SURVEY.md.
    """
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = basics.mesh()

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        mean_loss = collective_ops.allreduce(loss, op=Average, axis_name=axis_name)
        return TrainStepResult(params, opt_state, mean_loss)

    smapped = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name)),
        out_specs=TrainStepResult(P(), P(), P()),
        check_vma=False,
    )
    jitted = jax.jit(smapped, donate_argnums=(0, 1) if donate else ())
    if jax.default_backend() != "cpu":
        return jitted

    def throttled(params, opt_state, batch):
        # CPU-simulation only: XLA's in-process CPU collectives deadlock
        # (40 s rendezvous abort) when many launches of a collective module
        # are in flight at once — the N virtual devices share one thread
        # pool, so deep async dispatch can starve a device thread out of an
        # active rendezvous.  Blocking per step caps the in-flight depth at
        # 1; on TPU the async pipeline is left untouched.
        out = jitted(params, opt_state, batch)
        jax.block_until_ready(out.loss)
        return out

    return throttled


# ---------------------------------------------------------------------------
# State broadcast: model init sync and optimizer-state sync.
# ---------------------------------------------------------------------------


def _root_process(root_rank: int) -> int:
    """Process index owning device rank ``root_rank`` on the world mesh —
    the single definition of the rank→process mapping used by every
    any-root broadcast."""
    return list(basics.mesh().devices.flat)[root_rank].process_index


def broadcast_parameters(params: Any, root_rank: int = 0) -> Any:
    """Make every process agree with the root's parameter pytree.

    Parity with reference ``hvd.broadcast_parameters``
    (horovod/torch/__init__.py:270-299) / ``BroadcastGlobalVariablesHook``
    (tensorflow/__init__.py:101-132).

    Single-controller: the controller already holds THE copy, so this
    re-places leaves with replicated sharding over the mesh (the
    device-broadcast XLA would emit) and returns them.  Multi-controller:
    the values of the process owning device ``root_rank`` travel to all
    hosts over DCN — a direct one-to-all when the root lives on process 0,
    else a process allgather + select (the reference supports any
    ``root_rank``, horovod/torch/__init__.py:270-299).
    """
    basics._require_init()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(
            params, is_source=jax.process_index() == _root_process(root_rank)
        )
    sharding = basics.replicated_sharding()
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sharding), params)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0) -> Any:
    """Broadcast an optax optimizer state pytree.

    The reference needs 100 lines of scalar→tensor wrapping and recursive
    cast callbacks because torch optimizer state mixes tensors and Python
    scalars (torch/__init__.py:302-418).  optax states are pytrees, so the
    only special case is non-array leaves (step counts as Python ints):
    they are wrapped, broadcast, and cast back.
    """
    basics._require_init()
    import numpy as np

    leaves, treedef = jax.tree.flatten(opt_state)
    py_types = [None if isinstance(l, jax.Array) else type(l) for l in leaves]
    wrapped = [jnp.asarray(l) for l in leaves]
    out = broadcast_parameters(wrapped, root_rank)

    def _restore(t, leaf):
        if t is None:
            return leaf
        if issubclass(t, np.ndarray):
            # np.ndarray(x) is the low-level buffer constructor (treats ints
            # as a shape!); np.asarray is the value-preserving conversion.
            return np.asarray(leaf)
        return t(leaf)

    restored = [_restore(t, leaf) for t, leaf in zip(py_types, out)]
    return jax.tree.unflatten(treedef, restored)


def _mesh_local_rows() -> int:
    """How many rows of the rank-major array this process owns — counted
    on the WORLD MESH, not jax.local_device_count(): a device-subset init
    may exclude some local devices from the mesh."""
    me = jax.process_index()
    return sum(
        1 for d in basics.mesh().devices.flat if d.process_index == me
    )


def _process_first_rows() -> dict[int, int]:
    """process index → first global rank (mesh device-order row) owned by
    that process.  Consults the actual mesh device order, like
    ``_root_process`` — mesh order is NOT guaranteed process-contiguous."""
    first: dict[int, int] = {}
    for r, d in enumerate(basics.mesh().devices.flat):
        first.setdefault(d.process_index, r)
    return first


def _process_rank_major(local) -> jax.Array:
    """This process's payload, tiled to its local device rows of the global
    rank-major array (every local device carries the same bytes)."""
    import numpy as np

    rows = np.broadcast_to(local[None], (_mesh_local_rows(),) + local.shape)
    return jax.make_array_from_process_local_data(basics.rank_sharding(), rows)


def broadcast_object(obj: Any, root_rank: int = 0) -> Any:
    """Broadcast an arbitrary picklable object (the resume-epoch pattern of
    reference examples/keras_imagenet_resnet50.py:66-73).

    ``root_rank`` is a device rank; the object travels from the process
    that owns that device (any root works, like ``broadcast_parameters``).

    The wire goes THROUGH the eager engine, not an out-of-band host
    collective: multi-process XLA collectives are matched by arrival order
    on shared transport pairs, so an out-of-band broadcast racing the
    engine's cycle-thread dispatches can pair with the WRONG collective on
    a peer still draining engine traffic ("received data size doesn't
    match expected size").  Enqueueing serializes it with every queued
    engine op — the same reasoning as the torch frontend's
    shape negotiation (torch.py _negotiate_gather_shapes).
    """
    basics._require_init()
    if jax.process_count() == 1:
        return obj
    import pickle

    import numpy as np

    from horovod_tpu.ops import eager as eager_ops

    is_source = basics.cross_rank() == _root_process(root_rank)
    payload = (np.frombuffer(pickle.dumps(obj), np.uint8) if is_source
               else np.zeros((0,), np.uint8))
    length = np.asarray([payload.size], np.int32)
    h = eager_ops.broadcast_async(
        _process_rank_major(length), root_rank, name="bo.len"
    )
    n = int(np.asarray(jax.device_get(eager_ops.synchronize(h)))[0])
    if not is_source:
        payload = np.zeros((n,), np.uint8)
    h = eager_ops.broadcast_async(
        _process_rank_major(payload), root_rank, name="bo.payload"
    )
    data = jax.device_get(eager_ops.synchronize(h))
    return pickle.loads(bytes(bytearray(np.asarray(data))))


def allgather_object(obj: Any) -> list:
    """Gather one picklable object per PROCESS; every process receives the
    ``cross_size()``-long list ordered by process index.

    The object-level sibling of the eager ``allgather`` (an API later
    Horovod versions grew; natural here for gathering per-host metrics or
    shapes).  Wire format: lengths all-gather, pad to max, bytes
    all-gather, unpickle — all THROUGH the engine queue (see
    :func:`broadcast_object` for why out-of-band host collectives are a
    cross-rank ordering hazard).
    """
    basics._require_init()
    if jax.process_count() == 1:
        return [obj]
    import pickle

    import numpy as np

    from horovod_tpu.ops import eager as eager_ops

    payload = pickle.dumps(obj)
    h = eager_ops.allgather_async(
        _process_rank_major(np.asarray([[len(payload)]], np.int32)),
        name="ao.len",
    )
    lengths = np.asarray(
        jax.device_get(eager_ops.synchronize(h))
    ).reshape(-1)                                       # [size] (per device)
    pad = int(lengths.max())
    buf = np.frombuffer(payload.ljust(pad, b"\0"), np.uint8)
    h = eager_ops.allgather_async(
        _process_rank_major(buf[None]), name="ao.payload"
    )
    data = np.asarray(
        jax.device_get(eager_ops.synchronize(h))
    ).reshape(-1, pad)                                  # [size, pad]
    # One row per participating process, in process-index order, located
    # through the mesh's actual device order (not an assumed contiguity).
    return [
        pickle.loads(bytes(bytearray(data[r]))[: int(lengths[r])])
        for _, r in sorted(_process_first_rows().items())
    ]
