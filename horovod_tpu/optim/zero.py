"""ZeRO-style sharded optimizer — reduce-scatter → shard update → all-gather.

No reference equivalent (the reference replicates optimizer state on every
worker, like pre-ZeRO data parallelism everywhere); this is the TPU-native
memory-scaling extension.  The dataflow per step, inside one compiled SPMD
program over the ``hvd`` axis:

1. Every rank computes local gradients (standard backward).
2. The flattened gradient vector is ``psum_scatter``-ed: each rank receives
   the *reduced* 1/n-th it owns (half the wire cost of a full allreduce —
   the reduce-scatter leg the reference's hierarchical allreduce uses
   internally, operations.cc:1135-1158, promoted to the whole step).
3. The optimizer update runs on the rank's shard only — optimizer state
   (Adam moments etc.) lives at 1/n per chip.  ZeRO stages 1+2.
4. The updated parameter shard is ``all_gather``-ed back to a full vector.

Works with **elementwise** optax transforms (adam/adamw/sgd/rmsprop/…):
each parameter element's update depends only on its own gradient/state.
Transforms that need global statistics across the whole pytree would see
per-shard statistics — for the common case, gradient clipping, pass
``clip_global_norm=`` instead: the true global norm is one extra ``psum``
of per-shard squared norms, computed on the *reduced* gradient exactly as
``optax.clip_by_global_norm`` would see it in the replicated setup.

Memory per chip: params P (replicated) + reduced grads P/n + opt state
S/n, versus P + P + S for the replicated wrapper — for Adam (S = 2P) on
8 chips, optimizer+gradient memory drops from 3P to ~0.4P.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.basics import AXIS_NAME
from horovod_tpu.utils.compat import shard_map as _shard_map


class ZeroStepResult(NamedTuple):
    params: Any
    opt_state: Any       # sharded: array leaves hold the rank's 1/n slice
    loss: jax.Array


def make_zero_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    *,
    mesh: jax.sharding.Mesh | None = None,
    axis_name: str = AXIS_NAME,
    clip_global_norm: float | None = None,
    donate: bool = True,
) -> tuple[Callable[..., ZeroStepResult], Callable[[Any], Any]]:
    """Build a ZeRO train step; returns ``(step, init_opt_state)``.

    ``step(params, opt_state, batch) -> ZeroStepResult`` with replicated
    params and rank-sharded opt_state; ``batch`` leaves are rank-major.
    ``init_opt_state(params)`` creates the sharded state (each rank
    initializes only its own flat slice).

    The optimizer operates on ONE flat vector shard per rank, so its state
    arrays are ``[ceil(P/n)]`` regardless of the parameter pytree; scalar
    state leaves (step counts) stay replicated.  Programs are built once
    per parameter structure and cached.

    ``donate`` (default True): the input ``params``/``opt_state`` buffers
    are donated to the step — do not reuse them after calling; keep the
    returned ones (pass ``donate=False`` to keep inputs alive, at the cost
    of holding two parameter copies during the step).
    """
    if mesh is None:
        mesh = basics.mesh()
    n = int(mesh.devices.size)
    built: dict = {}

    def _build(params):
        # Cache key from structure + leaf shapes/dtypes only — no data
        # movement on the hot path (ravel_pytree concatenates the whole
        # pytree on device, which must happen once per structure, not once
        # per step).
        key = (
            jax.tree.structure(params),
            tuple((l.shape, jnp.dtype(l.dtype).name)
                  for l in jax.tree.leaves(params)),
        )
        if built.get("key") == key:
            return built
        flat0, unravel = ravel_pytree(params)
        total = int(flat0.shape[0])
        per = -(-total // n)                 # ceil: padded shard length
        pad = per * n - total
        # Optimizer-state layout for one shard: arrays shard over the axis,
        # scalars (e.g. Adam's count) replicate.
        shapes = jax.eval_shape(
            optimizer.init, jax.ShapeDtypeStruct((per,), flat0.dtype)
        )
        opt_specs = jax.tree.map(
            lambda l: P(axis_name) if len(l.shape) else P(), shapes
        )

        def my_slice(flat):
            idx = lax.axis_index(axis_name)
            padded = jnp.pad(flat, (0, pad)) if pad else flat
            return lax.dynamic_slice(padded, (idx * per,), (per,))

        def init_inner(flat):
            return optimizer.init(my_slice(flat))

        init_jitted = jax.jit(
            _shard_map(
                init_inner, mesh=mesh, in_specs=P(), out_specs=opt_specs,
                check_vma=False,
            )
        )

        def step_inner(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            gflat, _ = ravel_pytree(grads)
            gflat = (jnp.pad(gflat, (0, pad)) if pad else gflat) / n  # mean
            gshard = lax.psum_scatter(gflat, axis_name, tiled=True)   # [per]
            if clip_global_norm is not None:
                # True global norm from shard pieces: ||g||² = Σ_ranks ||g_r||²
                # (shards are disjoint).  Matches optax.clip_by_global_norm
                # on the replicated full gradient.
                gsq = lax.psum(jnp.sum(gshard.astype(jnp.float32) ** 2),
                               axis_name)
                gnorm = jnp.sqrt(gsq)
                scale = jnp.minimum(1.0, clip_global_norm / (gnorm + 1e-16))
                gshard = gshard * scale.astype(gshard.dtype)
            pshard = my_slice(ravel_pytree(params)[0])
            updates, opt_state = optimizer.update(gshard, opt_state, pshard)
            pshard = optax.apply_updates(pshard, updates)
            pfull = lax.all_gather(pshard, axis_name, tiled=True)[:total]
            return ZeroStepResult(
                unravel(pfull), opt_state, lax.pmean(loss, axis_name)
            )

        step_jitted = jax.jit(
            _shard_map(
                step_inner, mesh=mesh,
                in_specs=(P(), opt_specs, P(axis_name)),
                out_specs=ZeroStepResult(P(), opt_specs, P()),
                check_vma=False,
            ),
            # Donate params/opt_state (shapes+shardings match outputs) so
            # the step doesn't hold duplicate replicated-param buffers —
            # the memory headroom is the feature's point.
            donate_argnums=(0, 1) if donate else (),
        )
        built.update(key=key, init=init_jitted, step=step_jitted)
        return built

    def init_opt_state(params):
        b = _build(params)
        return b["init"](ravel_pytree(params)[0])

    def step(params, opt_state, batch):
        b = _build(params)
        out = b["step"](params, opt_state, batch)
        if jax.default_backend() == "cpu":
            # Same CPU-simulation dispatch-depth throttle as make_train_step.
            jax.block_until_ready(out.loss)
        return out

    return step, init_opt_state
