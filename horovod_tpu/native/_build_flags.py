"""Single source of truth for the native engine's compile line.

Imported by the first-use builder (``horovod_tpu.native.load_library``) and
loaded by path from ``setup.py``'s pre-build step, so wheels and first-use
builds can never drift apart on flags or source lists.  Stdlib-only: this
module must be importable in a build environment with no jax installed.
"""

CXX = "g++"
CXXFLAGS = ["-O2", "-std=c++17", "-fPIC", "-shared", "-pthread"]
SOURCES = ("controller.cc", "transport.cc", "c_api.cc")
HEADERS = ("controller.h", "transport.h", "types.h", "wire.h")


def compile_cmd(out_path: str, src_dir: str) -> list[str]:
    import os

    return [CXX, *CXXFLAGS, "-o", out_path] + [
        os.path.join(src_dir, s) for s in SOURCES
    ]
