"""ctypes binding to the native coordination engine (``native/src/``).

The reference loads its compiled engine with ``ctypes.CDLL(RTLD_GLOBAL)``
(reference: horovod/common/__init__.py:51-68).  Same approach here, with
one addition: if ``libhvdtpu.so`` is missing, it is compiled on first use
with ``g++`` from the in-tree sources — there is no wheel-building step in
a TPU pod image, and the engine has zero dependencies beyond libstdc++.

The native layer carries control-plane METADATA only (names, dtypes,
shapes, fused batch assignments); tensor payloads never leave device HBM —
the Python side dispatches one compiled XLA collective per returned batch.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from dataclasses import dataclass, field

from horovod_tpu.native import _build_flags

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SO_PATH = os.path.join(_HERE, "libhvdtpu.so")


def _find_src_dir() -> str:
    """Locate the native sources: repo layout first, then the copy the
    package build vendors into horovod_tpu/native/src (setup.py)."""
    for cand in (os.path.join(_REPO, "native", "src"),
                 os.path.join(_HERE, "src")):
        if os.path.exists(os.path.join(cand, "controller.cc")):
            return cand
    return os.path.join(_REPO, "native", "src")


_SRC_DIR = _find_src_dir()

# OpKind / DType wire values — must match native/src/types.h.
KIND_ALLREDUCE, KIND_ALLGATHER, KIND_BROADCAST, KIND_SPARSE = 0, 1, 2, 3
KIND_ALLTOALL, KIND_REDUCESCATTER, KIND_JOIN = 4, 5, 6

# Dispatch-program codes (types.h OpCode): what a JOINED rank must launch
# to participate in a batch it never submitted.
OP_PLAIN_SUM, OP_PLAIN_AVERAGE, OP_OTHER = 0, 1, 2

_DTYPE_CODES = {
    "uint8": 0, "int8": 1, "uint16": 2, "int16": 3, "int32": 4,
    "int64": 5, "float16": 6, "bfloat16": 7, "float32": 8, "float64": 9,
    "bool": 10, "uint32": 11, "uint64": 12,
}
DTYPE_NAMES = {v: k for k, v in _DTYPE_CODES.items()}

_build_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _sources() -> list[str]:
    srcs = [os.path.join(_SRC_DIR, f) for f in _build_flags.SOURCES]
    headers = [os.path.join(_SRC_DIR, f) for f in _build_flags.HEADERS]
    return srcs + [h for h in headers if os.path.exists(h)]


def _so_stale() -> bool:
    if not os.path.exists(_SO_PATH):
        return True
    if _SRC_DIR != os.path.join(_REPO, "native", "src"):
        # Installed layout: pip extracts files with arbitrary mtimes, so a
        # wheel's prebuilt .so must be trusted as-is, never "refreshed" —
        # a rebuild there would discard the prebuild (or fail on read-only
        # site-packages / missing g++).  Staleness only means anything in
        # the repo layout, where sources are actually edited.
        return False
    so_mtime = os.path.getmtime(_SO_PATH)
    return any(os.path.getmtime(s) > so_mtime for s in _sources()
               if os.path.exists(s))


def _build_so() -> None:
    srcs = [s for s in _sources() if s.endswith(".cc")]
    if not all(os.path.exists(s) for s in srcs):
        raise NativeBuildError(
            f"native sources not found under {_SRC_DIR}; "
            "cannot build libhvdtpu.so"
        )
    # Compile to a per-pid temp path and rename into place: rename is atomic
    # on one filesystem, so concurrent first-use builds from multiple local
    # ranks can never dlopen a partially-written .so.
    tmp = f"{_SO_PATH}.tmp.{os.getpid()}"
    cmd = _build_flags.compile_cmd(tmp, _SRC_DIR)
    # hvdlint: disable=HVD008 -- one-shot cold-start g++ build, intentionally serialized under _build_lock before any engine thread exists
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            "building libhvdtpu.so failed:\n" + proc.stderr[-2000:]
        )
    os.replace(tmp, _SO_PATH)


def load_library() -> ctypes.CDLL:
    """Load (building if needed) the native engine library."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if _so_stale():
            _build_so()
        lib = ctypes.CDLL(_SO_PATH, mode=ctypes.RTLD_GLOBAL)
        lib.hvdtpu_controller_create.restype = ctypes.c_void_p
        lib.hvdtpu_controller_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.hvdtpu_controller_destroy.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_controller_submit.restype = ctypes.c_int
        lib.hvdtpu_controller_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_ubyte, ctypes.c_ubyte, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_ubyte,
        ]
        lib.hvdtpu_controller_request_shutdown.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_controller_tick.restype = ctypes.c_int
        lib.hvdtpu_controller_tick.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hvdtpu_controller_stall_report.restype = ctypes.c_int
        lib.hvdtpu_controller_stall_report.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hvdtpu_controller_enable_tick_trace.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.hvdtpu_controller_set_tuned.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_double,
        ]
        lib.hvdtpu_controller_drain_ticks.restype = ctypes.c_int
        lib.hvdtpu_controller_drain_ticks.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hvdtpu_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
        _lib = lib
        return _lib


def available() -> bool:
    try:
        load_library()
        return True
    except (NativeBuildError, OSError):
        return False


@dataclass
class Batch:
    kind: int
    error: str
    names: list[str] = field(default_factory=list)
    # Wire dtype code + dispatch-program code + per-name shapes: a JOINED
    # rank reconstructs the exact collective for tensors it never saw.
    dtype: int = 8  # kF32
    op_code: int = OP_OTHER
    shapes: list[tuple[int, ...]] = field(default_factory=list)


@dataclass
class BatchList:
    shutdown: bool
    batches: list[Batch] = field(default_factory=list)
    # Rank-0-tuned knobs piggybacked on the response (None = unset); every
    # rank observes a move in the same tick (control-plane autotune).
    tuned_threshold_bytes: int | None = None
    tuned_cycle_ms: float | None = None
    # >= 0 once every rank has joined (hvd.join): the last rank to join.
    last_joined: int = -1


def _parse_batch_list(data: bytes) -> BatchList:
    # Mirrors native/src/wire.h SerializeBatchList.
    off = 0

    def u8():
        nonlocal off
        v = data[off]
        off += 1
        return v

    def u32():
        nonlocal off
        (v,) = struct.unpack_from("<I", data, off)
        off += 4
        return v

    def i64():
        nonlocal off
        (v,) = struct.unpack_from("<q", data, off)
        off += 8
        return v

    def s():
        n = u32()
        nonlocal off
        v = data[off:off + n].decode()
        off += n
        return v

    def i32():
        nonlocal off
        (v,) = struct.unpack_from("<i", data, off)
        off += 4
        return v

    shutdown = u8() != 0
    thr = i64()
    cyc_us = i64()
    last_joined = i32()
    batches = []
    for _ in range(u32()):
        kind = u8()
        dtype = u8()
        op_code = u8()
        error = s()
        names = [s() for _ in range(u32())]
        shapes = [
            tuple(i64() for _ in range(u32())) for _ in range(len(names))
        ]
        batches.append(Batch(kind, error, names, dtype=dtype,
                             op_code=op_code, shapes=shapes))
    return BatchList(
        shutdown, batches,
        tuned_threshold_bytes=thr if thr >= 0 else None,
        tuned_cycle_ms=cyc_us / 1000.0 if cyc_us >= 0 else None,
        last_joined=last_joined,
    )


class NativeController:
    """Python handle on one rank's native coordination controller."""

    def __init__(self, rank: int, size: int, transport_spec: str,
                 fusion_threshold_bytes: int, stall_warning_s: float = 60.0):
        lib = load_library()
        err = ctypes.create_string_buffer(512)
        self._lib = lib
        self._ptr = lib.hvdtpu_controller_create(
            rank, size, transport_spec.encode(), fusion_threshold_bytes,
            stall_warning_s, err, len(err),
        )
        if not self._ptr:
            raise RuntimeError(
                f"native controller init failed: {err.value.decode()}"
            )
        self.rank, self.size = rank, size

    def submit(self, kind: int, dtype: str, name: str,
               shape: tuple[int, ...], root_rank: int = 0,
               group: int = -1, op_code: int = OP_OTHER) -> None:
        code = _DTYPE_CODES.get(str(dtype))
        if code is None:
            raise ValueError(f"dtype {dtype} not supported by the native wire")
        arr = (ctypes.c_longlong * len(shape))(*shape)
        rc = self._lib.hvdtpu_controller_submit(
            self._ptr, kind, code, name.encode(), arr, len(shape),
            root_rank, group, op_code,
        )
        if rc != 0:
            raise RuntimeError(f"native submit rejected request {name!r}")

    def submit_join(self) -> None:
        """Flip this rank's joined bit (hvd.join): its missing submissions
        stop blocking readiness from the next tick."""
        rc = self._lib.hvdtpu_controller_submit(
            self._ptr, KIND_JOIN, 4, b"__join__", None, 0, 0, -1, OP_OTHER,
        )
        if rc != 0:
            raise RuntimeError("native submit rejected the join request")

    def tick(self) -> BatchList:
        if not self._ptr:
            return BatchList(shutdown=True)
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = ctypes.c_uint64()
        rc = self._lib.hvdtpu_controller_tick(
            self._ptr, ctypes.byref(out), ctypes.byref(n))
        if rc < 0:
            raise RuntimeError("native controller tick failed (transport)")
        try:
            data = ctypes.string_at(out, n.value)
        finally:
            self._lib.hvdtpu_free(out)
        return _parse_batch_list(data)

    def request_shutdown(self) -> None:
        self._lib.hvdtpu_controller_request_shutdown(self._ptr)

    def stall_report(self) -> str:
        if not self._ptr:
            return ""
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = ctypes.c_uint64()
        self._lib.hvdtpu_controller_stall_report(
            self._ptr, ctypes.byref(out), ctypes.byref(n))
        try:
            return ctypes.string_at(out, n.value).decode()
        finally:
            self._lib.hvdtpu_free(out)

    def set_tuned(self, threshold_bytes: int = -1,
                  cycle_ms: float = -1.0) -> None:
        """Install rank-0-tuned knobs (control-plane autotune).  Fusion
        batching is decided only by rank 0's controller, so a threshold set
        here governs the whole gang from the next tick; both values ride
        every response so all ranks observe the move together.  Negative =
        leave that knob unchanged; no-op off rank 0."""
        if self._ptr:
            self._lib.hvdtpu_controller_set_tuned(
                self._ptr, int(threshold_bytes), float(cycle_ms)
            )

    def enable_tick_trace(self, on: bool = True) -> None:
        """Record per-rank request arrivals on rank 0 (timeline NEGOTIATE
        ticks, reference timeline.cc:98-132).  Off by default."""
        if self._ptr:
            self._lib.hvdtpu_controller_enable_tick_trace(self._ptr, int(on))

    def drain_ticks(self) -> list[tuple[str, int]]:
        """Drain buffered (tensor_name, rank) arrival events (rank 0)."""
        if not self._ptr:
            return []
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = ctypes.c_uint64()
        self._lib.hvdtpu_controller_drain_ticks(
            self._ptr, ctypes.byref(out), ctypes.byref(n))
        try:
            text = ctypes.string_at(out, n.value).decode()
        finally:
            self._lib.hvdtpu_free(out)
        events = []
        for line in text.splitlines():
            rank_str, _, name = line.partition(" ")
            if name:
                events.append((name, int(rank_str)))
        return events

    def close(self) -> None:
        if self._ptr:
            self._lib.hvdtpu_controller_destroy(self._ptr)
            self._ptr = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
