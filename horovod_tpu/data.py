"""Sharded input pipeline — the reference's DistributedSampler recipe,
TPU-native.

The reference partitions datasets with torch's ``DistributedSampler``
(reference: examples/pytorch_mnist.py:50, pytorch_imagenet_resnet50.py:91-99)
so each of N processes sees 1/N of every epoch, reshuffled per epoch.  On
TPU the unit of parallelism is the chip, and the single-controller feeds
all local chips at once, so the native shape is: shard per *rank* (chip),
assemble the rank-major global batch, and hand XLA one sharded array per
step (placement onto chips is a zero-copy ``device_put`` with the
rank-major sharding).

Multi-host: every process builds batches only for its own ranks, and
``jax.make_array_from_process_local_data`` assembles the global array.
"""

from __future__ import annotations

import collections
import math
import queue
import threading
from typing import Any, Iterator

import jax
import numpy as np

from horovod_tpu import basics
from horovod_tpu import faults as faults_mod


def shard_indices(
    n: int,
    rank: int,
    size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = False,
) -> np.ndarray:
    """Index shard for one rank — the DistributedSampler contract: every
    rank gets the same count (padding by wrap-around, like the reference's
    sampler), reshuffled per epoch via ``seed + epoch``."""
    if shuffle:
        order = np.random.default_rng(seed + epoch).permutation(n)
    else:
        order = np.arange(n)
    if drop_last:
        per = n // size
        total = per * size
        order = order[:total]
    else:
        per = math.ceil(n / size)
        total = per * size
        if total > n:
            # Wrap as many times as needed (a dataset can be smaller than
            # the world; torch's sampler repeats indices the same way).
            order = np.tile(order, math.ceil(total / n))[:total]
    return order[rank * per:(rank + 1) * per]


class ShardedLoader:
    """Epoch iterator yielding rank-major global batches.

    ``data`` is a pytree of equal-length arrays (numpy or array-like).
    Each yielded batch is a pytree whose leaves have shape
    ``[size * batch_per_rank, ...]`` laid out rank-major (rank i's samples
    occupy rows ``[i*b, (i+1)*b)``) and placed with the rank-sharded
    ``NamedSharding`` — ready for :func:`horovod_tpu.make_train_step`.
    """

    def __init__(
        self,
        data: Any,
        batch_per_rank: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        device_put: bool = True,
        prefetch: int = 2,
    ):
        """``prefetch``: batches assembled ahead on a background thread
        (host-side fancy-indexing + async H2D overlap the device step —
        the input-pipeline overlap a tf.data prefetch gives the
        reference's examples).  0 disables the thread entirely."""
        # Convert leaves to numpy ONCE — doing it per batch would copy the
        # whole dataset every step for list/jax.Array inputs.
        data = jax.tree.map(np.asarray, data)
        leaves = jax.tree.leaves(data)
        if not leaves:
            raise ValueError("ShardedLoader: empty data pytree")
        self._n = len(leaves[0])
        for leaf in leaves:
            if len(leaf) != self._n:
                raise ValueError(
                    "ShardedLoader: all data leaves must share length; got "
                    f"{len(leaf)} vs {self._n}"
                )
        self.data = data
        self.batch_per_rank = batch_per_rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.device_put = device_put
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        self.prefetch = prefetch
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Reference ``train_sampler.set_epoch(epoch)`` parity."""
        self.epoch = epoch

    def __len__(self) -> int:
        size = basics.size()
        per_rank = (
            self._n // size if self.drop_last else math.ceil(self._n / size)
        )
        return per_rank // self.batch_per_rank

    def _batches(self) -> Iterator[Any]:
        size = basics.size()
        steps = len(self)
        b = self.batch_per_rank
        sharding = basics.rank_sharding() if self.device_put else None
        multi = self.device_put and jax.process_count() > 1
        if multi:
            # Each process assembles ONLY its own ranks' rows (in mesh
            # device order) and contributes them as its local shards —
            # never a host-global array: device_put of a host value onto a
            # cross-process sharding both copies the whole batch on every
            # host and runs a per-batch cross-host equality collective,
            # which can misorder against in-flight engine traffic.
            me = jax.process_index()
            ranks = [r for r, d in enumerate(basics.mesh().devices.flat)
                     if d.process_index == me]
        else:
            ranks = list(range(size))
        # Index shards only for the ranks this process actually feeds —
        # each shard_indices call is a full O(n) permutation, and on a big
        # pod computing all `size` of them per host per epoch is size×
        # the necessary work.
        shards = {
            r: shard_indices(
                self._n, r, size,
                shuffle=self.shuffle, seed=self.seed, epoch=self.epoch,
                drop_last=self.drop_last,
            )
            for r in ranks
        }
        for s in range(steps):
            # Rank-major assembly: rank i's slice is rows [i*b, (i+1)*b).
            idx = np.concatenate(
                [shards[r][s * b:(s + 1) * b] for r in ranks]
            )

            def take(leaf):
                out = leaf[idx]
                if multi:
                    return jax.make_array_from_process_local_data(
                        sharding, out
                    )
                return jax.device_put(out, sharding) if sharding else out

            yield jax.tree.map(take, self.data)

    def __iter__(self) -> Iterator[Any]:
        if self.prefetch <= 0:
            yield from self._batches()
            return
        # Bounded-queue producer thread: batch s+1's host assembly and
        # (async) H2D run while the training loop consumes batch s.  An
        # abandoned iterator (break mid-epoch) unblocks the producer via
        # the stop flag checked around every put.
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _END = object()

        def put_or_abandon(item) -> bool:
            """Blocking put that keeps honoring the stop flag — EVERY
            producer put must go through here, or an abandoned iterator
            with a full queue wedges the thread (and its queued device
            batches) for the process lifetime."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for i, batch in enumerate(self._batches()):
                    # deterministic fault site (key = batch index): an
                    # injected fault rides the existing exception
                    # propagation below, so tests can pin that a dying
                    # producer surfaces in the consumer instead of
                    # wedging the queue
                    faults_mod.check("data.producer", key=i)
                    if not put_or_abandon(batch):
                        return
                put_or_abandon(_END)
            except BaseException as exc:  # propagate into the consumer
                put_or_abandon(exc)

        t = threading.Thread(
            target=producer, name="horovod_tpu-prefetch", daemon=True
        )
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()


def synthetic_mnist(n: int = 4096, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped synthetic data ([N,28,28,1] float32 in
    [0,1], labels 0-9).  The reference examples download real MNIST; TPU
    pods run hermetic, so the examples ship with a synthetic stand-in and
    accept a path to real data."""
    rng = np.random.default_rng(seed)
    images = rng.random((n, 28, 28, 1), dtype=np.float32)
    labels = rng.integers(0, 10, size=(n,), dtype=np.int64)
    # Make labels learnable from pixels so example losses actually fall:
    # brighten a label-dependent patch.
    for d in range(10):
        mask = labels == d
        images[mask, 2 + 2 * (d % 5), 4 + 3 * (d // 5), 0] = 2.0
    return images, labels


def synthetic_imagenet(
    n: int = 256, image_size: int = 224, num_classes: int = 1000, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Synthetic ImageNet-shaped batch source (reference
    pytorch_synthetic_benchmark.py uses random data the same way)."""
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((n, image_size, image_size, 3)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=(n,), dtype=np.int64)
    return images, labels


def prefetch_to_device(
    iterator: Iterator[Any], size: int = 2, sharding: Any = None
) -> Iterator[Any]:
    """Keep ``size`` batches' device transfers in flight ahead of the
    consumer.

    ``ShardedLoader`` already overlaps H2D with compute when built with a
    ``sharding``; this is the standalone equivalent for user-supplied
    iterators (e.g. a torch ``DataLoader`` driven through the torch
    frontend, the reference's main data path — its examples get this
    overlap from ``DataLoader(num_workers=..., pin_memory=True)``).
    ``jax.device_put`` is asynchronous, so enqueueing batch s+``size``
    while the step consumes batch s hides the transfer latency; with no
    ``sharding`` the default device placement is used.

    Yields every input item exactly once, in order; an abandoned iterator
    drops its in-flight transfers with no thread to unwind (unlike the
    loader's producer, nothing here blocks).
    """
    if size < 1:   # validate at the call site, not at first next()
        raise ValueError(f"size must be >= 1, got {size}")

    # Multi-host with a cross-process sharding: each process must feed only
    # its LOCAL shards (make_array_from_process_local_data) — a bare
    # device_put of host data onto non-addressable devices raises or runs
    # per-batch out-of-band host collectives that can misorder against
    # in-flight engine traffic (same hazard ShardedLoader._batches guards).
    multi = sharding is not None and jax.process_count() > 1

    def put_leaf(x):
        if multi:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding) if sharding is not None \
            else jax.device_put(x)

    def put(item):
        return jax.tree.map(put_leaf, item)

    def gen():
        buf: collections.deque = collections.deque()
        for item in iterator:
            buf.append(put(item))
            if len(buf) > size:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    return gen()
