"""Deterministic fault injection for the serving/data stack.

Production fault tolerance is untestable without reproducible faults:
a "sometimes the device step raises" bug report is useless, and
sleep-based chaos harnesses make CI flaky.  This registry is the
step-counted alternative — every named **site** in the codebase calls
:meth:`FaultRegistry.check` on each pass, and test-installed rules fire
on the k-th hit of a site (optionally scoped to one request key,
optionally transient), so a fault schedule is a pure function of the
engine's step sequence: same schedule, same workload → same faults,
bit-for-bit.  No wall clock, no randomness.

Named sites currently wired:

=================  ========================================================
``serve.prefill``  per prefill window, per slot (key = request id) —
                   :class:`~horovod_tpu.serving_scheduler.ServeEngine`
``serve.tick``     per decode-tick readback, per decoding row (key =
                   request id)
``serve.admit``    per admission attempt (key = request id)
``serve.cache``    per prefix-cache lookup during admission (key =
                   request id) — fires BEFORE the radix match takes
                   any block references, so a fault quarantines to the
                   one request while every shared block stays intact
``serve.draft``    per drafting row per spec tick (key = request id) —
                   a firing drafter degrades that row to plain decode
                   for the round; drafting is an optimization, so the
                   request itself never fails or retries
``serve.router``   per replica pump iteration in the
                   :class:`~horovod_tpu.router.RouterServer` fleet
                   (key = replica name) — a firing rule kills that
                   replica; the router re-enqueues its in-flight
                   requests to survivors (replay keeps outputs
                   bit-identical)
``serve.supervisor``  per respawn attempt in the
                   :class:`~horovod_tpu.supervisor.ReplicaSupervisor`
                   (key = replica name) — a firing rule fails that
                   attempt, burning one unit of the replica's restart
                   budget and advancing its backoff
``serve.autoscale``  per actuation attempt in the
                   :class:`~horovod_tpu.autoscaler.FleetAutoscaler`
                   (key = action name) — a firing rule degrades that
                   actuation to ``hold``; routing and in-flight
                   requests are untouched, so a faulted autoscaler
                   never drops a request
``router.journal``  per append to the router's request-journal WAL
                   (key = record kind) — a firing rule loses that
                   record (the request is still served; durability
                   degrades, counted in ``router.journal_errors``)
``data.producer``  per batch assembled by the
                   :class:`~horovod_tpu.data.ShardedLoader` prefetch
                   thread (key = batch index)
=================  ========================================================

Rules raise :class:`TransientFault` (the consumer may retry — the
engine's bounded-retry-with-backoff path) or :class:`PermanentFault`
(retrying is pointless; fail the implicated request immediately).  A
transient rule stops firing after ``count`` hits, modeling a fault that
clears (a dropped RPC, a transient readback error); a permanent rule
fires on every matching hit from ``on_hit`` onward.

Engines take an explicit ``faults=`` registry (tests own their
schedules); module-level sites with no natural plumbing (the data
producer thread) check the shared :data:`DEFAULT` registry, which is
empty — and therefore free — unless a test arms it.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

from horovod_tpu import metrics as metrics_mod


class FaultError(RuntimeError):
    """Base class for injected faults; carries the site, the matched
    request key, and which hit of the site fired."""

    def __init__(self, site: str, key: Any, hit: int):
        super().__init__(
            f"injected fault at site {site!r} (key={key!r}, hit {hit})")
        self.site = site
        self.key = key
        self.hit = hit


class TransientFault(FaultError):
    """A fault that is expected to clear — consumers may retry."""


class PermanentFault(FaultError):
    """A fault that will not clear — consumers must not retry."""


@dataclasses.dataclass
class FaultRule:
    """One scheduled fault: fire at the ``on_hit``-th matching hit of
    ``site`` (1-based, counted per rule over hits whose key matches).

    ``count``: how many consecutive matching hits fire (transient rules
    only — a rule with ``permanent=True`` fires on every hit from
    ``on_hit`` onward).  ``key=None`` matches every hit of the site;
    otherwise only hits carrying exactly this key count toward (and
    trigger) the rule.
    """

    site: str
    on_hit: int = 1
    count: int = 1
    permanent: bool = False
    key: Any = None
    seen: int = 0       # matching hits observed so far
    fired: int = 0      # times this rule raised

    def matches(self, site: str, key: Any) -> bool:
        return self.site == site and (self.key is None or self.key == key)

    def should_fire(self) -> bool:
        if self.permanent:
            return self.seen >= self.on_hit
        return self.on_hit <= self.seen < self.on_hit + self.count


class FaultRegistry:
    """A set of :class:`FaultRule` plus per-site hit counters and a log
    of fired faults.  Thread-safe: the data-producer site checks from a
    background thread while the test thread reads the log."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.rules: list[FaultRule] = []
        self.log: list[tuple[str, Any, int]] = []   # (site, key, hit)
        self._hits: dict[str, int] = {}

    def inject(self, site: str, *, on_hit: int = 1, count: int = 1,
               permanent: bool = False, key: Any = None) -> FaultRule:
        """Arm a rule; returns it (its ``seen``/``fired`` counters are
        live, so tests can assert exactly when it triggered)."""
        if on_hit < 1:
            raise ValueError("on_hit is 1-based and must be >= 1")
        if count < 1:
            raise ValueError("count must be >= 1")
        rule = FaultRule(site=site, on_hit=on_hit, count=count,
                         permanent=permanent, key=key)
        with self._lock:
            self.rules.append(rule)
        return rule

    def check(self, site: str, key: Any = None) -> None:
        """Record one hit of ``site``; raise if an armed rule fires.

        The first matching rule that fires wins; every matching rule's
        ``seen`` counter advances regardless, so schedules compose
        (e.g. a transient fault on hit 2 and a permanent one on hit 5).
        """
        with self._lock:
            self._hits[site] = self._hits.get(site, 0) + 1
            firing: FaultRule | None = None
            for rule in self.rules:
                if not rule.matches(site, key):
                    continue
                rule.seen += 1
                if firing is None and rule.should_fire():
                    firing = rule
            if firing is None:
                return
            firing.fired += 1
            self.log.append((site, key, firing.seen))
            exc = PermanentFault if firing.permanent else TransientFault
        # Outside the lock: the shared event log / counter have their own
        # locks, and a fired fault is rare enough to afford the stamps.
        metrics_mod.DEFAULT.counter(f"faults.fired.{site}").inc()
        metrics_mod.DEFAULT.event(
            "fault", site=site, key=key, hit=firing.seen,
            permanent=firing.permanent)
        raise exc(site, key, firing.seen)

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def clear(self) -> None:
        """Drop every rule, counter, and log entry (test teardown)."""
        with self._lock:
            self.rules.clear()
            self.log.clear()
            self._hits.clear()


#: Shared registry for sites with no explicit plumbing (``data.producer``).
#: Empty — and therefore a cheap no-op — unless a test arms it; tests
#: that do MUST :func:`clear` it on teardown.
DEFAULT = FaultRegistry()


def inject(site: str, **kwargs: Any) -> FaultRule:
    """Arm a rule on the shared :data:`DEFAULT` registry."""
    return DEFAULT.inject(site, **kwargs)


def check(site: str, key: Any = None) -> None:
    """Check the shared :data:`DEFAULT` registry (module-level sites)."""
    DEFAULT.check(site, key)


def clear() -> None:
    """Reset the shared :data:`DEFAULT` registry."""
    DEFAULT.clear()
