"""Canonical registry of every environment knob the package reads.

``ENV_KNOBS`` is the single source of truth for the ``HOROVOD_*`` /
``HVD_TPU_*`` configuration surface: one ``(name, default, help)`` row
per knob.  The hvdlint HVD003 checker enforces membership both ways —
every getenv site in the package must have a row here, every row must
have a live read site, and the docs table in ``docs/observability.md``
must match this table exactly (regenerate it with
``python -m horovod_tpu.knobs``).

The table MUST stay a pure literal: hvdlint extracts it by AST
``literal_eval`` without importing this module (so the linter never
pulls in jax).  Keep rows sorted by name; an empty default means
"unset" (the reader treats absence and empty string the same).
"""

from __future__ import annotations

import collections

# name, default (as the env string; "" = unset), one-line help.
ENV_KNOBS = (
    ("HOROVOD_AUTOTUNE", "0",
     "Enable online (fusion-threshold, cycle-time) autotuning."),
    ("HOROVOD_AUTOTUNE_LOG", "",
     "CSV file receiving one row per autotune sample."),
    ("HOROVOD_AUTOTUNE_STEADY_STATE_SAMPLES", "10",
     "Samples per tuning point after warmup before scoring it."),
    ("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "3",
     "Samples discarded after each knob change before measuring."),
    ("HOROVOD_CYCLE_TIME", "5.0",
     "Background dispatch-loop cycle time in milliseconds."),
    ("HOROVOD_FUSION_THRESHOLD", "67108864",
     "Tensor-fusion bucket size in bytes (64 MiB default)."),
    ("HOROVOD_HIERARCHICAL_ALLREDUCE", "0",
     "Two-level (intra-host reduce, inter-host allreduce) dispatch."),
    ("HOROVOD_SPARSE_ALLREDUCE", "0",
     "Gradient-sparsity-aware allreduce for IndexedSlices-style updates."),
    ("HOROVOD_STALL_CHECK_DISABLE", "0",
     "Disable the stalled-negotiation warning thread."),
    ("HOROVOD_STALL_CHECK_TIME", "60.0",
     "Seconds a rank may lag negotiation before a stall warning."),
    ("HOROVOD_TIMELINE", "",
     "Chrome-trace timeline output path (enables timeline recording)."),
    ("HOROVOD_TPU_CONTROLLER_TRANSPORT", "",
     "Native control-plane transport: tcp:<host>:<port> or local:<world>."),
    ("HOROVOD_TPU_COORDINATOR", "",
     "host:port of the rank-0 coordinator for multi-process init."),
    ("HOROVOD_TPU_ELASTIC_RETRIES", "3",
     "Elastic-training restarts allowed before giving up."),
    ("HOROVOD_TPU_FORCE_PLATFORM", "",
     "Force a jax platform (cpu/tpu) instead of auto-detection."),
    ("HOROVOD_TPU_HIERARCHY_LOCAL_SIZE", "0",
     "Inner mesh extent for hierarchical dispatch (0 = local devices)."),
    ("HOROVOD_TPU_LOCAL_RANK", "",
     "This process's rank within its host (launcher-provided)."),
    ("HOROVOD_TPU_LOCAL_SIZE", "",
     "Number of processes on this host (launcher-provided)."),
    ("HOROVOD_TPU_NATIVE_CONTROLLER", "auto",
     "Native coordination engine: auto, on, or off."),
    ("HOROVOD_TPU_NUM_PROCESSES", "",
     "World size for multi-process init (unset = single process)."),
    ("HOROVOD_TPU_PROCESS_ID", "",
     "This process's global rank (launcher-provided)."),
    ("HOROVOD_TPU_SERIALIZE_DISPATCH", "auto",
     "Depth-1 dispatch serialization: auto (CPU only), on, or off."),
    ("HOROVOD_TPU_X64", "0",
     "Enable 64-bit jax types for the torch-compat surface."),
    ("HVD_TPU_ALERTS", "1",
     "Evaluate ALERT_RULES over the sampled series (0 = off)."),
    ("HVD_TPU_AUTOSCALE", "0",
     "Actuate CapacityAdvisor recommendations from the router poller."),
    ("HVD_TPU_AUTOSCALE_COOLDOWN_S", "30",
     "Seconds the autoscaler rests between actuations."),
    ("HVD_TPU_AUTOSCALE_MAX_REPLICAS", "8",
     "Fleet size ceiling the autoscaler will not grow past."),
    ("HVD_TPU_AUTOSCALE_MIN_REPLICAS", "1",
     "Fleet size floor the autoscaler will not shrink below."),
    ("HVD_TPU_AUTOSCALE_STABLE_S", "60",
     "Seconds of sustained shrink advice before a scale-down starts."),
    ("HVD_TPU_AUTOSCALE_STEP", "1",
     "Replicas added or retired per autoscaler action at most."),
    ("HVD_TPU_BENCH_CACHE", "",
     "Directory for cached benchmark baselines (default: repo-local)."),
    ("HVD_TPU_DEVICE_POLL_S", "1.0",
     "Seconds between device memory_stats() polls (HBM gauges)."),
    ("HVD_TPU_DEVICE_TELEMETRY", "0",
     "Device telemetry plane in ServeEngine (cost model, MFU, HBM)."),
    ("HVD_TPU_DRAFT_K", "4",
     "Draft tokens proposed per slot per tick when speculation is on."),
    ("HVD_TPU_EVENT_LOG", "",
     "JSONL request-lifecycle event-log output path."),
    ("HVD_TPU_EVENT_LOG_MAX_MB", "",
     "Rotate the event log past this many MB, keeping one .1 "
     "generation (unset = unbounded)."),
    ("HVD_TPU_FLASH_BWD", "pallas",
     "Flash-attention backward implementation: pallas or blockwise."),
    ("HVD_TPU_LOAD_DURATION_S", "1.0",
     "Seconds of offered arrivals per saturation-sweep rung."),
    ("HVD_TPU_LOAD_LADDER", "",
     "Comma-separated offered-RPS rungs for the saturation sweep."),
    ("HVD_TPU_LOAD_PROCESS", "poisson",
     "Load-harness arrival process: poisson, bursty, or fixed."),
    ("HVD_TPU_LOAD_SEED", "0",
     "Seed for load-harness arrival schedules and request mixes."),
    ("HVD_TPU_LOAD_TIMEOUT_S", "60",
     "Seconds the load harness waits for late replies per rung."),
    ("HVD_TPU_MONITOR_PORT", "",
     "Port for the per-rank /metrics + /healthz HTTP exporter."),
    ("HVD_TPU_NEGOTIATE_TIMEOUT_S", "60",
     "Host-card negotiation deadline in seconds during init()."),
    ("HVD_TPU_PEAK_FLOPS", "",
     "Per-chip peak FLOP/s override for the serving-MFU denominator."),
    ("HVD_TPU_PROFILE", "0",
     "Per-tick phase profiling in ServeEngine (serve.phase.* metrics)."),
    ("HVD_TPU_PROFILE_WINDOW", "256",
     "Ticks in the profiler's rolling per-phase report window."),
    ("HVD_TPU_RETRACE_FATAL", "0",
     "Raise when the retrace sentry sees a jit cache grow mid-serve."),
    ("HVD_TPU_ROUTER_DRAIN_S", "5.0",
     "Seconds stop() waits for in-flight requests before shutting down."),
    ("HVD_TPU_ROUTER_IMBALANCE", "4",
     "Inflight gap above which prefix_affinity falls back to least_loaded."),
    ("HVD_TPU_ROUTER_JOURNAL", "",
     "Path of the crash-durable request-journal JSONL WAL (unset = off)."),
    ("HVD_TPU_ROUTER_JOURNAL_KEYS", "4096",
     "Idempotency-key results kept for dedup (LRU) and after compaction."),
    ("HVD_TPU_ROUTER_MAX_FAILOVERS", "3",
     "Failover replays allowed per request before it fails terminally."),
    ("HVD_TPU_ROUTER_MIN_FREE_KV", "0",
     "Fleet free-KV fraction floor below which the router sheds (0 = off)."),
    ("HVD_TPU_ROUTER_MIN_GOODPUT", "0",
     "Fleet goodput floor below which the router sheds load (0 = off)."),
    ("HVD_TPU_ROUTER_POLICY", "prefix_affinity",
     "RouterServer policy: round_robin, least_loaded, or prefix_affinity."),
    ("HVD_TPU_ROUTER_POLL_S", "0.05",
     "Seconds between router polls of replica health and snapshots."),
    ("HVD_TPU_ROUTER_PORT", "",
     "Port for the RouterServer HTTP front door (maybe_start_router)."),
    ("HVD_TPU_ROUTER_PROBE_FAILS", "3",
     "Consecutive failed probes before an HTTP replica is marked dead."),
    ("HVD_TPU_ROUTER_SHADOW_MAX_MB", "64",
     "Fleet-wide shadow prefix index byte ceiling in MB (<= 0 = unbounded)."),
    ("HVD_TPU_ROUTER_TICKET_TTL_S", "600",
     "Seconds a finished router ticket stays readable before reaping."),
    ("HVD_TPU_SAMPLE_S", "1.0",
     "Seconds between time-series samples of the registry (<= 0 = off)."),
    ("HVD_TPU_SCHED_POLICY", "fifo",
     "ServeEngine scheduler policy: fifo, priority, or edf."),
    ("HVD_TPU_SIM_REPLICAS", "200",
     "Simulated replica count for the default simfleet campaign."),
    ("HVD_TPU_SIM_REQUESTS", "100000",
     "Offered virtual request count for the default simfleet campaign."),
    ("HVD_TPU_SIM_SEED", "0",
     "Seed for the simfleet campaign (schedule, chaos, per-replica jitter)."),
    ("HVD_TPU_SLO_E2E_S", "0",
     "End-to-end latency SLO in seconds for goodput (0 = no SLO)."),
    ("HVD_TPU_SPEC", "0",
     "Self-drafting (prompt-lookup) speculative decode in ServeEngine."),
    ("HVD_TPU_STRAGGLER_WARN_S", "1.0",
     "Step-lag threshold in seconds before a straggler warning."),
    ("HVD_TPU_SUPERVISE_BACKOFF_S", "0.5",
     "Base respawn delay for a dead replica (doubles per restart)."),
    ("HVD_TPU_SUPERVISE_MAX_RESTARTS", "3",
     "Respawns per replica before the supervisor circuit-breaks it."),
    ("HVD_TPU_TP", "1",
     "Tensor-parallel degree of ServeEngine (chips per serving replica)."),
    ("HVD_TPU_TRACE_SAMPLE", "0",
     "Fraction of requests head-sampled into the causal tracing plane."),
    ("HVD_TPU_TRACE_SEED", "0",
     "Seed for the deterministic trace sampler and span-id derivation."),
    ("HVD_TPU_VERIFY_BLOCKS", "0",
     "Walk paged-KV block tables every serve tick (debug, slow)."),
)

Knob = collections.namedtuple("Knob", ("name", "default", "help"))


def knobs() -> tuple[Knob, ...]:
    """The registry as named tuples, sorted by name."""
    return tuple(Knob(*row) for row in ENV_KNOBS)


def render_markdown_table() -> str:
    """The docs/observability.md knob table (HVD003 lints the docs copy
    against ``ENV_KNOBS``; paste this output verbatim on drift)."""
    lines = ["| Knob | Default | Meaning |", "| --- | --- | --- |"]
    for k in knobs():
        default = f"`{k.default}`" if k.default else "*(unset)*"
        lines.append(f"| `{k.name}` | {default} | {k.help} |")
    return "\n".join(lines)


if __name__ == "__main__":
    print(render_markdown_table())
