"""Per-tick phase profiler for the serving engine.

Continuous-batching schedulers hide host-side stalls inside "decode
time": admission bookkeeping, chunked-prefill dispatch, the blocking
token readback, and per-request postprocessing all happen between two
device ticks, and a whole-step latency histogram cannot say which one
got slower.  vLLM and SGLang both ship per-phase step timing for
exactly this reason; :class:`TickProfiler` is that layer here, stdlib
only, threaded through :meth:`ServeEngine.step
<horovod_tpu.serving_scheduler.ServeEngine.step>`.

Design rules (the acceptance criteria of the profiler):

* **Free when disabled.**  The engine holds ``prof = None`` and every
  call site is a single ``is not None`` test — no wrapper objects, no
  no-op method dispatch on the hot path.
* **No new jit signatures when enabled.**  The profiler only reads
  ``time.perf_counter()`` and feeds host-side instruments; it never
  touches a traced value, so ``compile_cache_sizes()`` is unchanged
  (pinned by ``tests/test_profiler.py``).
* **Phases tile the tick.**  ``mark(phase)`` charges the time since the
  previous boundary, so the top-level :data:`PHASES` sum to the
  measured step wall time by construction (the final ``mark`` →
  ``return`` gap is a few statements of python).  :data:`SUB_PHASES`
  are attributed *inside* their parent via explicit ``add()`` intervals
  and are excluded from the coverage arithmetic.

Each tick lands in three sinks: per-phase histograms in the engine's
:class:`~horovod_tpu.metrics.MetricsRegistry` (``serve.phase.*_s``),
closed async spans named ``phase/<name>`` on the timeline (id = step,
aggregated by ``tools/timeline_summary.py``), and one
``serve.profile_tick`` structured event when the registry has a JSONL
sink (replayed by ``tools/profile_report.py``).  ``report()`` summarizes
a rolling window of the last ``HVD_TPU_PROFILE_WINDOW`` ticks — the
payload of ``metrics_snapshot()["profile"]`` and the monitor's
``/profile`` endpoint.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any

from horovod_tpu import metrics as metrics_mod

#: Top-level phases in ``step()`` order.  They TILE the tick — each is
#: measured boundary-to-boundary, so their sum equals the tick wall time.
#: Every engine produces exactly these; schema consumers (replay,
#: timeline aggregation, the bench arm) may rely on their presence.
PHASES = ("expire", "admit", "decode_dispatch", "device_sync",
          "sample_postprocess", "bookkeeping")

#: Extra top-level phases that fire only on spec-enabled engines
#: (``draft`` before dispatch, ``verify`` in place of part of
#: ``sample_postprocess``).  They tile the tick exactly like
#: :data:`PHASES` but are surfaced in ``report()`` only once observed,
#: so non-spec engines keep the PR-7 report schema byte-for-byte.
SPEC_PHASES = ("draft", "verify")

#: Nested sub-phases (explicit intervals inside a parent phase).  They
#: overlap their parent, so coverage math skips them.  The
#: ``device_sync`` pair is the device-telemetry split of the readback
#: wait: cost-model-predicted device compute vs host stall (only
#: emitted when the engine runs with ``device_telemetry``).
SUB_PHASES = ("admit.cache_acquire", "admit.prefill_dispatch",
              "device_sync.compute_est", "device_sync.host_stall")

_DEFAULT_WINDOW = 256

#: The timeline track profiler spans live on.
TRACK = "serving.profiler"


def _env_window() -> int:
    raw = os.environ.get("HVD_TPU_PROFILE_WINDOW", "")
    try:
        return int(raw) if raw else _DEFAULT_WINDOW
    except ValueError:
        return _DEFAULT_WINDOW


class TickProfiler:
    """Mark-based per-tick phase timer.

    The engine thread drives ``begin(step)`` → ``mark(phase)`` /
    ``add(sub_phase, t0, t1)`` → ``end()`` once per ``step()``; the
    monitor thread calls ``report()`` on scrape.  Only the rolling
    window crosses threads — the per-tick scratch state is engine-thread
    private by construction (one ``step()`` at a time)."""

    _GUARDED_BY_LOCK = ("_ring", "_n_ticks")

    def __init__(self, metrics: "metrics_mod.MetricsRegistry",
                 timeline: Any = None, window: int | None = None):
        window = _env_window() if window is None else window
        if window < 1:
            raise ValueError(f"profile window must be >= 1, got {window}")
        self.window = window
        self.metrics = metrics
        self.timeline = timeline
        self._lock = threading.Lock()
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=window)
        self._n_ticks = 0
        # engine-thread scratch (never read off-thread)
        self._cur: dict[str, float] = {}
        self._t0 = 0.0
        self._t_last = 0.0
        self._step = -1
        # Pre-bound histograms, registered by LITERAL name (the HVD005
        # contract) so the snapshot is schema-stable from tick 0 and the
        # hot path never does a registry lookup.
        self._hists = {
            "expire": metrics.histogram("serve.phase.expire_s"),
            "admit": metrics.histogram("serve.phase.admit_s"),
            "admit.cache_acquire":
                metrics.histogram("serve.phase.admit_cache_acquire_s"),
            "admit.prefill_dispatch":
                metrics.histogram("serve.phase.admit_prefill_dispatch_s"),
            "draft": metrics.histogram("serve.phase.draft_s"),
            "decode_dispatch":
                metrics.histogram("serve.phase.decode_dispatch_s"),
            "device_sync": metrics.histogram("serve.phase.device_sync_s"),
            "device_sync.compute_est": metrics.histogram(
                "serve.phase.device_sync_compute_est_s"),
            "device_sync.host_stall": metrics.histogram(
                "serve.phase.device_sync_host_stall_s"),
            "verify": metrics.histogram("serve.phase.verify_s"),
            "sample_postprocess":
                metrics.histogram("serve.phase.sample_postprocess_s"),
            "bookkeeping": metrics.histogram("serve.phase.bookkeeping_s"),
            "tick": metrics.histogram("serve.phase.tick_s"),
        }
        assert set(self._hists) == (set(PHASES) | set(SPEC_PHASES)
                                    | set(SUB_PHASES) | {"tick"})

    # -- hot path (engine thread) ------------------------------------------

    def begin(self, step: int) -> None:
        """Open a tick: resets the scratch dict and both clocks."""
        self._step = step
        self._cur = {}
        self._t0 = self._t_last = time.perf_counter()

    def mark(self, phase: str) -> None:
        """Close the current tiling boundary: charges ``phase`` with the
        time since the previous ``mark``/``begin``."""
        now = time.perf_counter()
        t0, self._t_last = self._t_last, now
        self._cur[phase] = self._cur.get(phase, 0.0) + (now - t0)
        if self.timeline is not None:
            self.timeline.async_span(TRACK, "phase/" + phase,
                                     self._step, t0, now)

    def add(self, phase: str, t0: float, t1: float) -> None:
        """Attribute an explicit ``[t0, t1]`` ``perf_counter`` interval
        to a nested sub-phase WITHOUT moving the tiling boundary (the
        parent phase still covers it)."""
        self._cur[phase] = self._cur.get(phase, 0.0) + (t1 - t0)
        if self.timeline is not None:
            self.timeline.async_span(TRACK, "phase/" + phase,
                                     self._step, t0, t1)

    def end(self) -> None:
        """Close the tick: the trailing time becomes ``bookkeeping``,
        every phase feeds its histogram, the tick joins the rolling
        window, and one ``serve.profile_tick`` event is emitted."""
        self.mark("bookkeeping")
        cur = self._cur
        cur["tick"] = self._t_last - self._t0
        for phase, dt in cur.items():
            h = self._hists.get(phase)
            if h is not None:
                h.observe(dt)
        with self._lock:
            self._ring.append(cur)
            self._n_ticks += 1
        self.metrics.event(
            "serve.profile_tick", step=self._step, tick_s=cur["tick"],
            phases={k: v for k, v in cur.items() if k != "tick"})

    # -- reporting (any thread) --------------------------------------------

    def report(self) -> dict:
        """Rolling-window per-phase summary: for each phase its sample
        count, total/mean/max seconds and share of tick time, plus the
        tick totals and ``coverage`` — the fraction of windowed tick
        wall time the top-level phases account for (≈ 1.0 by the tiling
        construction).  The same schema ``tools/profile_report.py``
        renders and diffs."""
        with self._lock:
            items = list(self._ring)
            n_ticks = self._n_ticks
        n = len(items)
        ticks = [it.get("tick", 0.0) for it in items]
        tick_total = sum(ticks)
        phases: dict[str, dict] = {}
        tiled = 0.0
        # Spec phases (and any future mark names) join the report only
        # once a tick actually recorded them — non-spec engines keep
        # the fixed PHASES schema.
        extra = sorted({k for it in items for k in it}
                       - set(PHASES) - set(SUB_PHASES) - {"tick"})
        for phase in PHASES + tuple(extra) + SUB_PHASES:
            vals = [it[phase] for it in items if phase in it]
            total = sum(vals)
            phases[phase] = {
                "count": len(vals),
                "total_s": total,
                "mean_s": total / len(vals) if vals else 0.0,
                "max_s": max(vals) if vals else 0.0,
                "pct_of_tick": (100.0 * total / tick_total
                                if tick_total else 0.0),
            }
            if phase not in SUB_PHASES:
                tiled += total
        return {
            "window": self.window,
            "n": n,
            "ticks": n_ticks,
            "tick": {
                "count": n,
                "total_s": tick_total,
                "mean_s": tick_total / n if n else 0.0,
                "max_s": max(ticks, default=0.0),
            },
            "phases": phases,
            "coverage": tiled / tick_total if tick_total else 1.0,
        }
