"""Open-loop load harness: production-shaped arrivals, saturation
curves, and end-to-end latency attribution (ROADMAP open item 4).

Every ``serve_*`` figure before this module is a **closed-loop**
rehearsal: the bench submits a fixed batch and measures
throughput-at-any-latency, the number the Horovod paper's own scaling
tables warn against trusting.  A real front door is **open-loop** —
clients arrive on their own clock and are never back-pressured by
completions, so offered load past the knee makes queues (and tail
latency) grow without bound instead of politely slowing the generator.
This module is that client population, stdlib-only, and fully
seed-deterministic:

* **Arrival processes** (:class:`FixedRate`, :class:`Poisson`,
  :class:`Bursty`) turn an offered rate into a reproducible arrival
  schedule.  ``Bursty`` is a two-state Markov-modulated Poisson
  process — calm/burst states with sticky transitions — because
  production traffic arrives in correlated clumps, and the clumps are
  exactly what closed-loop benches never show.

* **Multi-tenant request mixes** (:class:`TenantSpec`,
  :class:`RequestMix`): per-tenant prompt/output length ranges, a
  seeded shared-prefix corpus (the prefix-cache population the router's
  affinity policy exists for), per-tenant SLOs for goodput accounting,
  and an optional **poison blend** (malformed empty-prompt requests
  that must terminate ``REJECTED`` without hurting their neighbours).
  A chaos blend rides the existing fault registry via
  :func:`arm_chaos`.

* **Open-loop drivers**: :func:`run_open_loop` calls
  ``RouterServer.route()`` at each arrival instant (in-process);
  :func:`run_open_loop_http` POSTs the HTTP front door, one daemon
  thread per arrival.  Pacing comes from a :class:`WallClock` — or a
  :class:`VirtualClock` in tier-1 tests, which collapses the schedule
  to "as fast as possible" with zero sleeps while keeping the arrival
  *order and request sets* bit-identical.

* **Saturation sweep** (:func:`measure_saturation`): step offered RPS
  across a ladder, and for each rung report client-observed p50/p99
  TTFT / TPOT / e2e, shed/timeout rates, SLO goodput, and the
  **goodput knee** (the rung where delivered good work per second
  peaks — everything past it is queueing, not serving).

* **Latency attribution**: each record joins the router-side spans
  (:meth:`RouterServer.request_trace` — receive, admission, route
  decision, journal append, submit) with the engine-side
  :class:`~horovod_tpu.metrics.Trace` by rid.  The phases tile the
  client-observed e2e exactly — ingress, route, replica queue, engine
  queue-wait, prefill, decode, finish, egress — so the report can say
  *where* the p99 millisecond lives at each rung, and
  ``tools/load_report.py --compare`` can gate on it.

Knobs: ``HVD_TPU_LOAD_SEED`` / ``HVD_TPU_LOAD_PROCESS`` /
``HVD_TPU_LOAD_LADDER`` / ``HVD_TPU_LOAD_DURATION_S`` /
``HVD_TPU_LOAD_TIMEOUT_S`` (see ``docs/observability.md``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import random
import threading
import time
from typing import Any, Sequence

from horovod_tpu import tracing as tracing_mod
from horovod_tpu.monitor import env_float
from horovod_tpu.serving import (OK, REJECTED, TIMEOUT, Request)

#: Terminal status for an arrival whose reply never came back within
#: the harness timeout — still in flight somewhere, or dropped on the
#: floor by a dying fleet.  Counted into ``timeout_rate``.
LOST = "LOST"

#: The phases that tile a client-observed e2e latency, in causal
#: order.  ``ingress`` = client send -> router receive; ``route`` =
#: receive -> replica submit (admission + policy + journal append);
#: ``replica_queue`` = submit -> engine enqueue (the replica inbox);
#: ``queue_wait`` = enqueue -> first admission (engine scheduler);
#: ``prefill`` = admission -> first emitted token; ``decode`` = first
#: token -> terminal; ``finish`` = terminal -> router done;
#: ``egress`` = router done -> client receipt (HTTP reply path).
ATTR_PHASES = ("ingress_s", "route_s", "replica_queue_s",
               "queue_wait_s", "prefill_s", "decode_s", "finish_s",
               "egress_s")


# -- clocks ----------------------------------------------------------------


class WallClock:
    """Real-time pacing: ``sleep_until(t)`` sleeps to offset ``t``
    seconds after :meth:`start` (monotonic)."""

    def __init__(self) -> None:
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.monotonic()

    def now(self) -> float:
        if self._t0 is None:
            self.start()
        return time.monotonic() - self._t0

    def sleep_until(self, t: float) -> None:
        if self._t0 is None:
            self.start()
        delay = t - self.now()
        if delay > 0:
            time.sleep(delay)


class VirtualClock:
    """Zero-sleep pacing for tier-1 tests: ``sleep_until`` advances a
    virtual cursor instantly, so a seeded schedule keeps its arrival
    order and request sets but the driver never blocks.  Latency
    figures then measure the fleet at max pressure — which is exactly
    the regime a saturation test wants."""

    def __init__(self) -> None:
        self._t = 0.0

    def start(self) -> None:
        self._t = 0.0

    def now(self) -> float:
        return self._t

    def sleep_until(self, t: float) -> None:
        self._t = max(self._t, t)


# -- arrival processes -----------------------------------------------------


class FixedRate:
    """Deterministic evenly-spaced arrivals at ``rate`` per second —
    the closed-form control every stochastic process is judged
    against."""

    name = "fixed"

    def __init__(self, rate: float, seed: int = 0) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = rate

    def times(self, duration_s: float) -> tuple[float, ...]:
        n = int(math.floor(self.rate * duration_s))
        return tuple(i / self.rate for i in range(n))


class Poisson:
    """Memoryless arrivals: exponential inter-arrival gaps at ``rate``
    per second.  A fresh ``random.Random(seed)`` per :meth:`times`
    call makes the schedule a pure function of ``(rate, seed,
    duration)`` — call it twice, get the same schedule."""

    name = "poisson"

    def __init__(self, rate: float, seed: int = 0) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = rate
        self.seed = seed

    def times(self, duration_s: float) -> tuple[float, ...]:
        rng = random.Random(f"poisson:{self.seed}:{self.rate!r}")
        out: list[float] = []
        t = rng.expovariate(self.rate)
        while t < duration_s:
            out.append(t)
            t += rng.expovariate(self.rate)
        return tuple(out)


class Bursty:
    """Two-state Markov-modulated Poisson: sticky calm/burst states in
    ``dwell_s`` slots, Poisson arrivals within each slot at the state's
    rate.  The burst state runs ``burst``x the calm rate and occupies
    ``frac`` of slots at stationarity, with the calm rate scaled so
    the long-run mean is still ``rate`` — same offered load as
    :class:`Poisson`, clumpier arrivals."""

    name = "bursty"

    def __init__(self, rate: float, seed: int = 0, *,
                 burst: float = 4.0, frac: float = 0.25,
                 dwell_s: float = 0.25, persist: float = 0.5) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0")
        if not 0.0 < frac < 1.0:
            raise ValueError("frac must be in (0, 1)")
        self.rate = rate
        self.seed = seed
        self.burst = burst
        self.frac = frac
        self.dwell_s = dwell_s
        self.persist = persist

    def times(self, duration_s: float) -> tuple[float, ...]:
        rng = random.Random(f"bursty:{self.seed}:{self.rate!r}")
        lo = self.rate / ((1.0 - self.frac) + self.frac * self.burst)
        hi = lo * self.burst
        # Sticky chain with the requested stationary burst fraction:
        # P(stay burst) = persist, P(enter burst | calm) solves
        # frac = enter / (enter + 1 - persist).
        enter = self.frac * (1.0 - self.persist) / (1.0 - self.frac)
        in_burst = rng.random() < self.frac
        out: list[float] = []
        t0 = 0.0
        while t0 < duration_s:
            slot_end = min(t0 + self.dwell_s, duration_s)
            r = hi if in_burst else lo
            t = t0 + rng.expovariate(r)
            while t < slot_end:
                out.append(t)
                t += rng.expovariate(r)
            in_burst = (rng.random() < self.persist if in_burst
                        else rng.random() < enter)
            t0 += self.dwell_s
        return tuple(out)


PROCESSES: dict[str, type] = {p.name: p
                              for p in (FixedRate, Poisson, Bursty)}


def resolve_process(spec: "str | Any", rate: float, seed: int = 0):
    """``"poisson" | "bursty" | "fixed"`` (or an instance passthrough)
    to an arrival process at ``rate``."""
    if not isinstance(spec, str):
        return spec
    try:
        return PROCESSES[spec](rate, seed)
    except KeyError:
        raise ValueError(
            f"unknown arrival process {spec!r}; "
            f"one of {sorted(PROCESSES)}") from None


# -- tenants + request mixes -----------------------------------------------


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic shape: arrival weight, prompt/output token
    ranges, a shared-prefix population (``shared_prefixes`` distinct
    ``prefix_len``-token system prompts drawn from the seeded corpus),
    an SLO for goodput accounting, and a poison fraction (malformed
    empty-prompt requests the fleet must shrug off as ``REJECTED``)."""

    name: str
    weight: float = 1.0
    prompt_len: tuple[int, int] = (8, 24)
    new_tokens: tuple[int, int] = (4, 12)
    shared_prefixes: int = 0
    prefix_len: int = 16
    slo_s: float | None = None
    deadline_s: float | None = None
    poison: float = 0.0


#: The default two-tenant production shape: latency-sensitive
#: interactive traffic with a shared-prefix population (chatbot system
#: prompts) and a tight SLO, plus heavier batch traffic with a loose
#: one.  Token ids stay in [2, 90] — inside the tiny rehearsal vocab,
#: clear of 0/1 (pad / the disjoint warmup family).
DEFAULT_TENANTS: tuple[TenantSpec, ...] = (
    TenantSpec("interactive", weight=3.0, prompt_len=(4, 12),
               new_tokens=(4, 8), shared_prefixes=4, prefix_len=16,
               slo_s=2.0),
    TenantSpec("batch", weight=1.0, prompt_len=(16, 40),
               new_tokens=(8, 16), slo_s=10.0),
)


class RequestMix:
    """Seeded multi-tenant request sampler.  The shared-prefix corpus
    is built once per mix (a pure function of ``(seed, tenant)``), so
    every rung of a sweep draws suffixes against the same prefix
    population — the steady prompt families a prefix cache feeds on."""

    def __init__(self, tenants: Sequence[TenantSpec] = DEFAULT_TENANTS,
                 seed: int = 0, *, vocab_lo: int = 2,
                 vocab_hi: int = 90) -> None:
        if not tenants:
            raise ValueError("need at least one tenant")
        self.tenants = tuple(tenants)
        self.seed = seed
        self.vocab_lo = vocab_lo
        self.vocab_hi = vocab_hi
        self._weights = [t.weight for t in self.tenants]
        self._corpus: dict[str, list[list[int]]] = {}
        for t in self.tenants:
            rng = random.Random(f"corpus:{seed}:{t.name}")
            self._corpus[t.name] = [
                [rng.randint(vocab_lo, vocab_hi)
                 for _ in range(t.prefix_len)]
                for _ in range(t.shared_prefixes)]

    def sample(self, rng: random.Random) -> tuple[Request, TenantSpec,
                                                  bool]:
        """One ``(request, tenant, poison)`` draw from ``rng``."""
        tenant = rng.choices(self.tenants, weights=self._weights)[0]
        if tenant.poison > 0 and rng.random() < tenant.poison:
            # Malformed on purpose: the engine must answer REJECTED
            # without collateral damage (PR 9's poison hardening).
            return (Request(prompt=[],
                            max_new_tokens=max(tenant.new_tokens[0], 1)),
                    tenant, True)
        n_prompt = rng.randint(*tenant.prompt_len)
        prompt: list[int] = []
        prefixes = self._corpus[tenant.name]
        if prefixes:
            prompt.extend(rng.choice(prefixes))
        prompt.extend(rng.randint(self.vocab_lo, self.vocab_hi)
                      for _ in range(n_prompt))
        req = Request(prompt=prompt,
                      max_new_tokens=rng.randint(*tenant.new_tokens),
                      slo_s=tenant.slo_s,
                      deadline_s=tenant.deadline_s)
        return req, tenant, False


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled arrival: when (offset seconds from epoch start),
    what (the full request), and who (tenant name, poison flag)."""

    t: float
    req: Request
    tenant: str
    poison: bool


def build_schedule(process: Any, mix: RequestMix, duration_s: float,
                   seed: int = 0) -> tuple[Arrival, ...]:
    """The full offered workload for one rung, bit-reproducible: the
    process fixes *when*, the mix (driven by a ``Random(seed)``
    derived here) fixes *what*.  Same ``(process, mix, duration,
    seed)`` -> identical schedule, always."""
    rng = random.Random(f"schedule:{seed}")
    out = []
    for t in process.times(duration_s):
        req, tenant, poison = mix.sample(rng)
        out.append(Arrival(t, req, tenant.name, poison))
    return tuple(out)


def schedule_digest(schedule: Sequence[Arrival]) -> str:
    """Stable hex digest of a schedule's arrival times and request
    sets — the bit-reproducibility witness the sweep report carries."""
    h = hashlib.blake2b(digest_size=16)
    for a in schedule:
        h.update(repr((a.t, a.tenant, a.poison, a.req.prompt,
                       a.req.max_new_tokens, a.req.slo_s,
                       a.req.deadline_s)).encode())
    return h.hexdigest()


def arm_chaos(faults: Any, seed: int, n_faults: int,
              replica_names: Sequence[str]) -> list:
    """Blend a seeded fault storm into a load run via the existing
    registry: transient engine-site rules from the chaos module's
    schedule generator (coverage-first, then random spread).  Returns
    the armed rules."""
    from horovod_tpu.chaos import ChaosSchedule
    sched = ChaosSchedule.generate(seed, replica_names=replica_names,
                                   n_faults=n_faults, n_kills=0)
    return [rule.arm(faults) for rule in sched.rules]


# -- open-loop drivers -----------------------------------------------------


def run_open_loop(router: Any, schedule: Sequence[Arrival], *,
                  clock: Any = None,
                  timeout_s: float | None = None) -> list[dict]:
    """Drive a :class:`~horovod_tpu.router.RouterServer` in-process:
    ``route()`` fires at each arrival instant regardless of how many
    earlier requests are still in flight (open loop — completions
    never pace arrivals), then one collection pass joins results and
    merged traces.  Returns one record dict per arrival."""
    if timeout_s is None:
        timeout_s = env_float("HVD_TPU_LOAD_TIMEOUT_S", 60.0)
    clock = clock if clock is not None else WallClock()
    clock.start()
    frac = tracing_mod.env_sample_fraction()
    tseed = tracing_mod.env_trace_seed()
    fired: list[tuple[Arrival, int, float, Any]] = []
    for idx, a in enumerate(schedule):
        clock.sleep_until(a.t)
        ctx = None
        if frac > 0.0:
            # Client-origin trace root: the sampling key is a pure
            # function of the (seeded, deterministic) schedule, so the
            # sampled set replays bit-identically.
            ctx = tracing_mod.TraceContext.root(
                f"client:{idx}:{a.t!r}:{a.tenant}", "client",
                frac, tseed)
            a.req.trace_ctx = ctx
        send_ts = time.monotonic()
        rid = router.route(a.req)
        fired.append((a, rid, send_ts, ctx))
    records: list[dict] = []
    deadline = time.monotonic() + timeout_s
    for a, rid, send_ts, ctx in fired:
        remaining = max(deadline - time.monotonic(), 0.001)
        try:
            res = router.result(rid, timeout=remaining)
            trace = router.request_trace(rid) if res is not None else None
        except KeyError:            # reaped mid-collection
            res, trace = None, None
        if res is None:
            if ctx is not None:
                router.tracer.span(ctx, "client", send_ts,
                                   time.monotonic(), tenant=a.tenant,
                                   status=LOST)
            records.append(_record(
                a, rid, send_ts, None, LOST, 0, None,
                trace_id=ctx.trace_id if ctx is not None else None))
            continue
        router_done = (trace or {}).get("router", {}).get("done_ts")
        done_ts = router_done if router_done else time.monotonic()
        if ctx is not None:
            router.tracer.span(ctx, "client", send_ts, done_ts,
                               tenant=a.tenant, status=res.status)
        tid = (ctx.trace_id if ctx is not None else
               ((trace or {}).get("router") or {}).get("trace_id"))
        records.append(_record(a, rid, send_ts, done_ts,
                               res.status, len(res), trace,
                               trace_id=tid))
    return records


def run_open_loop_http(base_url: str, schedule: Sequence[Arrival], *,
                       clock: Any = None,
                       timeout_s: float | None = None,
                       tracer: Any = None) -> list[dict]:
    """Drive the HTTP front door open-loop: one daemon thread per
    arrival POSTs ``/v1/generate`` at its scheduled instant, client
    send/receive stamps wrap the wire.  Reply traces (the satellite-1
    ``trace`` dict) give the same attribution join as in-process —
    exact when router and client share a monotonic clock domain (the
    in-process-server rehearsal), durations-only when truly remote.
    Sampled arrivals carry their trace context on the ``traceparent``
    request header; pass ``tracer`` (e.g. ``router.tracer`` when the
    server is in-process) to also emit the client span itself."""
    from horovod_tpu.router import request_to_json
    if timeout_s is None:
        timeout_s = env_float("HVD_TPU_LOAD_TIMEOUT_S", 60.0)
    clock = clock if clock is not None else WallClock()
    clock.start()
    frac = tracing_mod.env_sample_fraction()
    tseed = tracing_mod.env_trace_seed()
    url = base_url.rstrip("/") + "/v1/generate"
    slots: list = [None] * len(schedule)
    ctxs: list = [None] * len(schedule)
    threads: list[threading.Thread] = []

    def _fire(idx: int, a: Arrival) -> None:
        import urllib.error
        import urllib.request
        headers = {"Content-Type": "application/json"}
        if ctxs[idx] is not None:
            headers["traceparent"] = ctxs[idx].to_header()
        send_ts = time.monotonic()
        try:
            http_req = urllib.request.Request(
                url, data=json.dumps(request_to_json(a.req)).encode(),
                headers=headers)
            try:
                with urllib.request.urlopen(
                        http_req, timeout=timeout_s) as resp:
                    body = json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                # 429 shed replies carry the same JSON body shape.
                body = json.loads(e.read().decode())
            slots[idx] = (send_ts, time.monotonic(), body)
        except Exception:
            slots[idx] = (send_ts, time.monotonic(), None)

    for idx, a in enumerate(schedule):
        clock.sleep_until(a.t)
        if frac > 0.0:
            ctxs[idx] = tracing_mod.TraceContext.root(
                f"client:{idx}:{a.t!r}:{a.tenant}", "client",
                frac, tseed)
        th = threading.Thread(target=_fire, args=(idx, a), daemon=True,
                              name=f"hvd-loadgen-{idx}")
        th.start()
        threads.append(th)
    deadline = time.monotonic() + timeout_s
    for th in threads:
        th.join(timeout=max(deadline - time.monotonic(), 0.001))
    records: list[dict] = []
    for idx, a in enumerate(schedule):
        got = slots[idx]
        ctx = ctxs[idx]
        if got is None or got[2] is None:
            send_ts = got[0] if got else time.monotonic()
            if ctx is not None and tracer is not None:
                tracer.span(ctx, "client", send_ts, time.monotonic(),
                            tenant=a.tenant, status=LOST)
            records.append(_record(
                a, -1, send_ts, None, LOST, 0, None,
                trace_id=ctx.trace_id if ctx is not None else None))
            continue
        send_ts, done_ts, body = got
        if ctx is not None and tracer is not None:
            tracer.span(ctx, "client", send_ts, done_ts,
                        tenant=a.tenant,
                        status=body.get("status", LOST))
        tid = (ctx.trace_id if ctx is not None else
               ((body.get("trace") or {}).get("router") or {})
               .get("trace_id"))
        records.append(_record(a, body.get("rid", -1), send_ts, done_ts,
                               body.get("status", LOST),
                               len(body.get("tokens") or []),
                               body.get("trace"), trace_id=tid))
    return records


def _record(a: Arrival, rid: int, send_ts: float,
            client_done_ts: float | None, status: str, n_tokens: int,
            trace: dict | None, *, trace_id: str | None = None) -> dict:
    """One arrival's outcome: client-observed latencies plus the
    per-phase attribution split (:data:`ATTR_PHASES`) and, when the
    arrival was head-sampled, its causal ``trace_id`` (the join key
    into ``tools/trace_report.py``)."""
    rec: dict[str, Any] = {
        "rid": rid, "tenant": a.tenant, "poison": a.poison,
        "sched_t": a.t, "status": status, "n_tokens": n_tokens,
        "slo_s": a.req.slo_s, "trace_id": trace_id,
        "e2e_s": None, "ttft_s": None, "tpot_s": None,
        "good": False, "attr": None,
    }
    if client_done_ts is not None:
        rec["e2e_s"] = max(client_done_ts - send_ts, 0.0)
    if trace:
        ft = trace.get("first_token_ts")
        if ft is not None:
            rec["ttft_s"] = max(ft - send_ts, 0.0)
        rec["tpot_s"] = trace.get("tpot_s")
        rec["attr"] = _attr(trace, send_ts, client_done_ts)
    rec["good"] = (status == OK
                   and (a.req.slo_s is None or rec["e2e_s"] is None
                        or rec["e2e_s"] <= a.req.slo_s))
    return rec


def _attr(trace: dict, send_ts: float,
          client_done_ts: float | None) -> dict:
    """Split one merged trace into the :data:`ATTR_PHASES` tiling.
    Every phase is a difference of adjacent stamps (clamped at 0), so
    present phases sum to the client e2e exactly — attribution
    coverage measures how much of the path had stamps, not how well
    the arithmetic balanced."""
    router = trace.get("router") or {}
    recv = router.get("recv_ts")
    submit = router.get("submit_ts")
    done = router.get("done_ts")
    enq = trace.get("enqueue_ts")
    admit = trace.get("admit_ts")
    ft = trace.get("first_token_ts")
    term = trace.get("terminal_ts")

    def span(a: float | None, b: float | None) -> float | None:
        if a is None or b is None:
            return None
        return max(b - a, 0.0)

    return {
        "ingress_s": span(send_ts, recv),
        "route_s": span(recv, submit),
        "replica_queue_s": router.get("replica_queue_s",
                                      span(submit, enq)),
        "queue_wait_s": trace.get("queue_wait_s", span(enq, admit)),
        "prefill_s": span(admit, ft),
        "decode_s": span(ft, term),
        "finish_s": router.get("finish_s", span(term, done)),
        "egress_s": span(done, client_done_ts),
    }


# -- rung summaries + the sweep --------------------------------------------


def percentile(values: Sequence[float], q: float) -> float:
    """Exact sample percentile with linear interpolation (0 on empty —
    the :func:`~horovod_tpu.metrics.percentile_from_buckets` empty
    stance)."""
    if not values:
        return 0.0
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    rank = min(max(q, 0.0), 1.0) * (len(vs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (rank - lo)


def attribute(records: Sequence[dict]) -> dict:
    """Mean per-phase latency split over the OK records, plus
    ``coverage`` — the fraction of mean e2e the named phases explain.
    The acceptance bar is coverage >= 0.95 at the knee: if a phase of
    the path loses its stamps, this number says so."""
    ok = [r for r in records
          if r["status"] == OK and r["attr"] and r["e2e_s"]]
    if not ok:
        return {"n": 0, "coverage": 0.0, "mean_e2e_s": 0.0,
                "phases": {p: 0.0 for p in ATTR_PHASES}}
    phases = {p: sum(r["attr"][p] or 0.0 for r in ok) / len(ok)
              for p in ATTR_PHASES}
    mean_e2e = sum(r["e2e_s"] for r in ok) / len(ok)
    return {"n": len(ok), "mean_e2e_s": mean_e2e, "phases": phases,
            "coverage": (sum(phases.values()) / mean_e2e
                         if mean_e2e > 0 else 0.0)}


def summarize_rung(records: Sequence[dict], *, offered_rps: float,
                   duration_s: float) -> dict:
    """One saturation-curve point: status mix, shed/timeout rates,
    client percentiles, SLO goodput, and the per-phase attribution."""
    n = max(len(records), 1)
    statuses: dict[str, int] = {}
    for r in records:
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    good = [r for r in records if r["good"]]
    e2es = [r["e2e_s"] for r in records if r["e2e_s"] is not None]
    ttfts = [r["ttft_s"] for r in records if r["ttft_s"] is not None]
    tpots = [r["tpot_s"] for r in records if r["tpot_s"] is not None]
    span_s = max(max((r["sched_t"] for r in records), default=0.0)
                 + (max(e2es) if e2es else 0.0), duration_s, 1e-9)
    # Exemplars: the slowest sampled requests of the rung — trace ids
    # a reader can feed straight to ``tools/trace_report.py`` to see
    # WHERE the rung's tail latency lives.
    tailed = sorted((r for r in records
                     if r.get("trace_id") and r["e2e_s"] is not None),
                    key=lambda r: r["e2e_s"], reverse=True)
    return {
        "offered_rps": offered_rps,
        "duration_s": duration_s,
        "n": len(records),
        "statuses": statuses,
        "ok_rate": statuses.get(OK, 0) / n,
        "shed_rate": statuses.get(REJECTED, 0) / n,
        "timeout_rate": (statuses.get(TIMEOUT, 0)
                         + statuses.get(LOST, 0)) / n,
        "p50_ttft_s": percentile(ttfts, 0.50),
        "p99_ttft_s": percentile(ttfts, 0.99),
        "p50_tpot_s": percentile(tpots, 0.50),
        "p99_tpot_s": percentile(tpots, 0.99),
        "p50_e2e_s": percentile(e2es, 0.50),
        "p99_e2e_s": percentile(e2es, 0.99),
        "goodput": len(good) / n,
        "goodput_rps": len(good) / span_s,
        "tokens": sum(r["n_tokens"] for r in records),
        "attribution": attribute(records),
        "exemplar_trace_ids": [r["trace_id"] for r in tailed[:3]],
    }


def _load_seed() -> int:
    try:
        return int(os.environ.get("HVD_TPU_LOAD_SEED", "0") or 0)
    except ValueError:
        return 0


def _load_ladder() -> "tuple[float, ...] | None":
    raw = os.environ.get("HVD_TPU_LOAD_LADDER", "")
    if not raw:
        return None
    return tuple(float(x) for x in raw.split(",") if x.strip())


def measure_saturation(
        params: Any = None, cfg: Any = None, *,
        engines: Sequence[Any] | None = None,
        ladder: Sequence[float] | None = None,
        seed: int | None = None,
        process: str | None = None,
        duration_s: float | None = None,
        timeout_s: float | None = None,
        tenants: Sequence[TenantSpec] | None = None,
        n_replicas: int = 2, n_slots: int = 4, chunk: int = 16,
        max_len: int | None = None, policy: Any = None,
        registry: Any = None, chaos_faults: int = 0,
        http: bool = False, clock: Any = None,
        keep_records: bool = False) -> dict:
    """The saturation sweep: step offered load across ``ladder`` rungs
    of ``duration_s`` of seeded open-loop arrivals each, against a
    fresh ``n_replicas`` fleet behind a
    :class:`~horovod_tpu.router.RouterServer`, and report the curve —
    percentiles and goodput per rung, the **goodput knee** (first rung
    of peak delivered-good-work per second), p99-TTFT monotonicity,
    and the per-phase latency attribution at the knee.

    Bit-reproducible by construction: rung ``i``'s schedule is a pure
    function of ``(seed, i, rate, duration)`` and the shared-prefix
    corpus is a pure function of ``(seed, tenants)`` — the per-rung
    ``schedule_digest`` in the report is the witness.  Pass ``engines``
    to sweep an existing fleet (tests), or ``params``/``cfg`` to build
    one.  ``http=True`` drives the started HTTP front door instead of
    in-process ``route()``.  Flat ``serve_load_*`` keys are the bench
    arm's contract; the full ``rungs`` list is what
    ``tools/load_report.py`` renders and gates on."""
    from horovod_tpu import faults as faults_mod
    from horovod_tpu.metrics import MetricsRegistry
    from horovod_tpu.router import RouterServer

    seed = _load_seed() if seed is None else seed
    if process is None:
        process = os.environ.get("HVD_TPU_LOAD_PROCESS", "") or "poisson"
    if ladder is None:
        ladder = _load_ladder() or (4.0, 16.0, 64.0, 256.0)
    if duration_s is None:
        duration_s = env_float("HVD_TPU_LOAD_DURATION_S", 1.0)
    if timeout_s is None:
        timeout_s = env_float("HVD_TPU_LOAD_TIMEOUT_S", 60.0)
    mix = RequestMix(tenants if tenants is not None else DEFAULT_TENANTS,
                     seed)
    reg = registry if registry is not None else MetricsRegistry()
    fr = faults_mod.FaultRegistry()
    if engines is None:
        from horovod_tpu.serving_scheduler import ServeEngine
        if max_len is None:
            need = (max(t.prefix_len + t.prompt_len[1]
                        + t.new_tokens[1] for t in mix.tenants) + chunk)
            max_len = -(-need // chunk) * chunk      # block-aligned
        engines = [ServeEngine(params, cfg, n_slots=n_slots,
                               max_len=max_len, chunk=chunk,
                               prefix_cache=True, metrics=reg,
                               faults=fr)
                   for _ in range(n_replicas)]
    # Untimed warmup on the disjoint [1]*k family: every rung pays
    # zero compile time, and the measured radix stays cold for the
    # workload's own prefixes.
    for eng in engines:
        eng.run([Request(prompt=[1] * (eng.chunk + 1),
                         max_new_tokens=2)])
    router = RouterServer(engines, policy=policy, registry=reg,
                          faults=fr)
    if chaos_faults:
        arm_chaos(fr, seed, chaos_faults,
                  [r.name for r in router.replicas])
    if http:
        router.start()
    rungs: list[dict] = []
    all_records: list[list[dict]] = []
    try:
        for i, rate in enumerate(ladder):
            rung_seed = seed * 8191 + 1000003 * (i + 1)
            sched = build_schedule(
                resolve_process(process, rate, rung_seed), mix,
                duration_s, rung_seed)
            if http:
                records = run_open_loop_http(
                    f"http://{router.host}:{router.port}", sched,
                    clock=clock, timeout_s=timeout_s)
            else:
                records = run_open_loop(router, sched, clock=clock,
                                        timeout_s=timeout_s)
            rung = summarize_rung(records, offered_rps=rate,
                                  duration_s=duration_s)
            rung["schedule_digest"] = schedule_digest(sched)
            rungs.append(rung)
            all_records.append(records)
    finally:
        router.stop()
    knee_i = max(range(len(rungs)),
                 key=lambda i: rungs[i]["goodput_rps"])
    knee = rungs[knee_i]
    # Monotone up to measurement jitter: a 1 ms / 5 % slack keeps two
    # equally-underloaded rungs from failing the flag on noise, and a
    # rung that drew < 2 arrivals has no percentile to rank.
    p99s = [r["p99_ttft_s"] for r in rungs if r["n"] >= 2]
    monotone = all(b >= a - max(0.001, 0.05 * a)
                   for a, b in zip(p99s, p99s[1:]))
    report: dict[str, Any] = {
        "serve_load_seed": seed,
        "serve_load_process": process,
        "serve_load_duration_s": duration_s,
        "serve_load_rungs": len(rungs),
        "serve_load_requests": sum(r["n"] for r in rungs),
        "serve_load_replicas": len(router.replicas),
        "serve_load_knee_rps": knee["offered_rps"],
        "serve_load_knee_goodput_rps": knee["goodput_rps"],
        "serve_load_p99_ttft_knee_ms": knee["p99_ttft_s"] * 1e3,
        "serve_load_p99_tpot_knee_ms": knee["p99_tpot_s"] * 1e3,
        "serve_load_attr_coverage_knee":
            knee["attribution"]["coverage"],
        "serve_load_p99_ttft_monotone": int(monotone),
        "serve_load_shed_rate_top": rungs[-1]["shed_rate"],
        "serve_load_timeout_rate_top": rungs[-1]["timeout_rate"],
        "ladder": list(ladder),
        "knee_index": knee_i,
        "knee_exemplar_trace_ids": knee["exemplar_trace_ids"],
        "rungs": rungs,
    }
    if keep_records:
        report["records"] = all_records
    return report
