"""Elastic autoscaling for the router fleet: close the advisor →
actuator loop.

PR 12's :class:`~horovod_tpu.alerts.CapacityAdvisor` emits
evidence-carrying ``scale_up(n)`` / ``scale_down(n)`` / ``hold``
records, and PR 10 built both actuators — supervisor respawn through a
pluggable factory (:func:`~horovod_tpu.supervisor.clone_engine`) and a
journal-backed drain that fails open into bit-identical replay.  The
:class:`FleetAutoscaler` connects them.  It holds no thread of its
own: the router's existing poll pass ticks it (the supervisor/sampler
idiom), after the health plane so every decision actuates against
this pass's fresh views.

**Grow** spawns a brand-new replica through the supervisor's factory
seam (:meth:`~horovod_tpu.supervisor.ReplicaSupervisor.spawn_replica`
— an explicit factory, or a clone of a live local replica pre-warmed
with the fleet's hot prefixes) and joins it with
:meth:`~horovod_tpu.router.RouterServer.add_replica`.

**Shrink** is cordon → drain → retire, and drops zero requests by
construction: ``cordon_replica`` removes the victim from the routing
candidate set while its in-flight requests keep running; once its
inflight count reaches zero the victim is retired
(:meth:`~horovod_tpu.router.RouterServer.retire_replica`).  A victim
that has not drained by the deadline is failed open instead of waited
on forever: it is killed through the same death path a crash takes,
so every in-flight request's callback fires ``None`` and the router
replays it on a survivor — greedy determinism makes the replay
bit-identical, and journaled idempotency keys stay exactly-once
because the dedup map and WAL survive the membership change.

Membership is explicit: a generation-numbered :class:`FleetEpoch`
bumps on every join and leave.  The bump is bookkeeping only — the
per-replica ``ShadowPrefixIndex`` objects, the advisor's history, and
the journal dedup map are deliberately NOT reset, which is what makes
scale-downs invisible to clients.

Victim selection is a pluggable :class:`VictimPolicy`.  The default,
:class:`LeastLocalityVictim`, retires the replica the prefix-affinity
plane values least: fewest shadow-index paths, ties broken by lowest
probed goodput, then by name for determinism.

Every actuation is guarded: a cooldown between actions, a
stabilization window of *sustained* shrink advice before any
scale-down starts (flap suppression), min/max replica bounds, and a
per-action step cap — all ``HVD_TPU_AUTOSCALE_*`` knobs.  Each
actuation attempt checks the ``serve.autoscale`` fault site first
(key = action name): a firing rule degrades the action to ``hold`` —
counted in ``autoscaler.hold_faults``, evented, and crucially never
touching routing, so a faulted autoscaler can never drop a request.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Sequence

from horovod_tpu import faults as faults_mod
from horovod_tpu.monitor import env_float


class FleetEpoch:
    """Generation-numbered fleet membership (the serving-side echo of
    ``elastic.py``'s commit step): every join/leave bumps the
    generation and records the member set, so any observer can name
    exactly which fleet a request was served by."""

    def __init__(self, members: Sequence[str] = (),
                 history: int = 64):
        self._gen = 0
        self._members = tuple(members)
        self._history: collections.deque[dict] = collections.deque(
            maxlen=history)

    @property
    def generation(self) -> int:
        return self._gen

    @property
    def members(self) -> tuple[str, ...]:
        return self._members

    def bump(self, members: Sequence[str], reason: str,
             t: float) -> int:
        self._gen += 1
        self._members = tuple(members)
        self._history.append({"generation": self._gen, "t": t,
                              "members": self._members,
                              "reason": reason})
        return self._gen

    def snapshot(self) -> dict:
        return {"generation": self._gen,
                "members": list(self._members),
                "history": list(self._history)}


class VictimPolicy:
    """Chooses which replica a scale-down retires.  ``candidates`` is
    the healthy, uncordoned name list; ``views`` the last-probed
    per-replica views; ``shadows`` the per-replica shadow prefix
    indexes (both read under the router lock by the caller)."""

    name = "victim"

    def choose(self, candidates: Sequence[str], views: dict,
               shadows: dict) -> str:
        raise NotImplementedError


class LeastLocalityVictim(VictimPolicy):
    """Retire the replica the prefix-affinity plane values least:
    fewest shadow-index paths (its cached prefixes are cheapest to
    lose), goodput-tiebroken (among equals, the one serving worst
    goes), then name for determinism."""

    name = "least_locality"

    def choose(self, candidates: Sequence[str], views: dict,
               shadows: dict) -> str:
        def key(n: str) -> tuple:
            shadow = shadows.get(n)
            paths = len(shadow) if shadow is not None else 0
            goodput = views.get(n, {}).get("goodput", 1.0)
            return (paths, goodput, n)
        return min(candidates, key=key)


class FleetAutoscaler:
    """Actuates :class:`~horovod_tpu.alerts.CapacityAdvisor` records
    against one router; see the module docstring.

    Ticked by the router's poller (it sets ``router.autoscaler`` on
    construction, like the supervisor).  ``enabled=False`` keeps the
    full decision trail (``report()``, ``/autoscaler``) in advisory
    mode without ever touching membership; in-flight drains still
    converge, so disabling mid-scale-down cannot strand a cordon.
    """

    _GUARDED_BY_LOCK = ("_draining", "_history", "_last_decision")

    def __init__(self, router: Any, *,
                 supervisor: Any = None,
                 advisor: Any = None,
                 victim_policy: "VictimPolicy | None" = None,
                 enabled: "bool | None" = None,
                 cooldown_s: "float | None" = None,
                 stable_s: "float | None" = None,
                 min_replicas: "int | None" = None,
                 max_replicas: "int | None" = None,
                 step: "int | None" = None,
                 drain_s: "float | None" = None,
                 eval_s: "float | None" = None,
                 faults: "faults_mod.FaultRegistry | None" = None,
                 history: int = 64,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self._explicit_supervisor = supervisor
        self._explicit_advisor = advisor
        self.victim_policy = (victim_policy if victim_policy is not None
                              else LeastLocalityVictim())
        self.enabled = (enabled if enabled is not None else
                        os.environ.get("HVD_TPU_AUTOSCALE", "0")
                        not in ("", "0"))
        self.cooldown_s = (cooldown_s if cooldown_s is not None else
                           env_float("HVD_TPU_AUTOSCALE_COOLDOWN_S",
                                     30.0))
        self.stable_s = (stable_s if stable_s is not None else
                         env_float("HVD_TPU_AUTOSCALE_STABLE_S", 60.0))
        self.min_replicas = max(1, int(
            min_replicas if min_replicas is not None else
            env_float("HVD_TPU_AUTOSCALE_MIN_REPLICAS", 1)))
        self.max_replicas = int(
            max_replicas if max_replicas is not None else
            env_float("HVD_TPU_AUTOSCALE_MAX_REPLICAS", 8))
        self.step = max(1, int(
            step if step is not None else
            env_float("HVD_TPU_AUTOSCALE_STEP", 1)))
        # Per-victim drain deadline before failing open (the router's
        # shutdown-drain budget is the natural default).
        self.drain_s = (drain_s if drain_s is not None
                        else getattr(router, "drain_s", 5.0))
        sampler = getattr(router, "sampler", None)
        self.eval_s = (eval_s if eval_s is not None else
                       getattr(sampler, "sample_s", 1.0) or 1.0)
        self.faults = faults if faults is not None else router.faults
        self.metrics = router.metrics
        self.clock = clock
        self._lock = threading.Lock()
        self._history: collections.deque[dict] = collections.deque(
            maxlen=history)
        self._draining: dict[str, dict] = {}
        self._last_decision: "dict | None" = None
        self._last_eval = float("-inf")
        self._last_action_ts = float("-inf")
        self._shrink_since: "float | None" = None
        self._spawn_seq = 0
        with router._lock:
            members = [r.name for r in router.replicas]
        self.epoch = FleetEpoch(members)
        # Registered up front (literal names — the HVD005 contract).
        self.metrics.counter("autoscaler.actions")
        self.metrics.counter("autoscaler.scale_ups")
        self.metrics.counter("autoscaler.scale_downs")
        self.metrics.counter("autoscaler.holds")
        self.metrics.counter("autoscaler.hold_faults")
        self.metrics.counter("autoscaler.cordons")
        self.metrics.gauge("autoscaler.epoch").set(0)
        self.metrics.gauge("autoscaler.draining").set(0)
        self.metrics.gauge("autoscaler.replicas_target").set(
            len(members))
        router.autoscaler = self

    # -- wiring ------------------------------------------------------------

    @property
    def supervisor(self) -> Any:
        return (self._explicit_supervisor
                if self._explicit_supervisor is not None
                else getattr(self.router, "supervisor", None))

    @property
    def advisor(self) -> Any:
        return (self._explicit_advisor
                if self._explicit_advisor is not None
                else getattr(self.router, "advisor", None))

    # -- the decision loop -------------------------------------------------

    def tick(self, now: "float | None" = None) -> "dict | None":
        """One autoscaling pass (the router's poller calls this every
        poll): converge in-flight drains first, then — at the eval
        cadence, when enabled — consume one advisor recommendation
        and actuate it under the guards.  Returns the decision record
        when one was evaluated, else ``None``."""
        now = self.clock() if now is None else now
        self._advance_drains(now)
        if now - self._last_eval < self.eval_s:
            return None
        self._last_eval = now
        if not self.enabled:
            return None
        advisor = self.advisor
        if advisor is None:
            return None
        rec = advisor.recommend(now)
        return self.actuate(rec, now)

    def actuate(self, rec: dict, now: "float | None" = None) -> dict:
        """Actuate one recommendation record (``tick`` calls this with
        the advisor's; campaigns script their own).  Every path —
        including every guard — produces a decision record in the
        history, so ``report()`` explains inaction as well as
        action."""
        now = self.clock() if now is None else now
        action = rec.get("action", "hold")
        n = int(rec.get("n", 0) or 0)
        with self.router._lock:
            size = len(self.router.replicas)
            draining = len(self._draining)
        held = self._guard(action, n, size, draining, now)
        if held is None and action != "hold":
            try:
                self.faults.check("serve.autoscale", key=action)
            except Exception as e:
                self.metrics.counter("autoscaler.hold_faults").inc()
                held = f"actuation fault: {e}"
        if held is not None:
            decision = self._decide(now, rec, "hold", held)
        elif action == "scale_up":
            decision = self._grow(rec, n, size, now)
        elif action == "scale_down":
            decision = self._shrink(rec, n, size, now)
        else:
            # The advisor's own hold: the steady state.  Track it as
            # the last decision but keep it out of the history and
            # the event log — an idle fleet must not spam either.
            decision = self._decide(now, rec, "hold",
                                    rec.get("reason", "advisor hold"),
                                    record=False)
        if decision["action"] == "hold":
            self.metrics.counter("autoscaler.holds").inc()
        return decision

    def _guard(self, action: str, n: int, size: int, draining: int,
               now: float) -> "str | None":
        """The actuation guards; returns the hold reason, or ``None``
        to proceed.  Also drives the scale-down stabilization window:
        shrink advice must be *continuous* for ``stable_s`` before a
        cordon starts, and any other advice resets the window."""
        if action != "scale_down":
            self._shrink_since = None
        if action == "hold" or n <= 0:
            return None if action == "hold" else "empty recommendation"
        if draining:
            return "a scale-down is still draining"
        if now - self._last_action_ts < self.cooldown_s:
            return (f"cooldown: {now - self._last_action_ts:.2f}s "
                    f"since last action < {self.cooldown_s:g}s")
        if action == "scale_up":
            if size >= self.max_replicas:
                return f"at max_replicas={self.max_replicas}"
            return None
        # scale_down: stabilization window (flap suppression).
        if self._shrink_since is None:
            self._shrink_since = now
        if now - self._shrink_since < self.stable_s:
            return (f"stabilizing: shrink advice for "
                    f"{now - self._shrink_since:.2f}s < "
                    f"{self.stable_s:g}s")
        if size <= self.min_replicas:
            return f"at min_replicas={self.min_replicas}"
        return None

    # -- actuation ---------------------------------------------------------

    def _grow(self, rec: dict, n: int, size: int, now: float) -> dict:
        target = min(size + min(n, self.step), self.max_replicas)
        sup = self.supervisor
        if sup is None:
            return self._decide(now, rec, "hold",
                                "no supervisor factory seam to spawn "
                                "through")
        joined: list[str] = []
        for _ in range(target - size):
            name = self._fresh_name()
            try:
                handle = sup.spawn_replica(name)
            except Exception as e:
                self.metrics.counter("autoscaler.hold_faults").inc()
                self.metrics.event("autoscaler.spawn_failure",
                                   replica=name, error=str(e))
                break
            if handle is None:
                break       # out-of-band fleet: nothing to join here
            self.router.add_replica(handle)
            joined.append(name)
            self.metrics.counter("autoscaler.scale_ups").inc()
        if not joined:
            return self._decide(now, rec, "hold",
                                "grow produced no replica (factory "
                                "unavailable or failed)")
        gen = self._bump_epoch("scale_up", now)
        self._last_action_ts = now
        self.metrics.counter("autoscaler.actions").inc()
        self.metrics.gauge("autoscaler.replicas_target").set(target)
        for name in joined:
            self.metrics.event("autoscaler.scale_up", replica=name,
                               epoch=gen, reason=rec.get("reason"))
        return self._decide(now, rec, "scale_up",
                            f"joined {joined} at epoch {gen}",
                            replicas=joined, epoch=gen)

    def _shrink(self, rec: dict, n: int, size: int,
                now: float) -> dict:
        target = max(size - min(n, self.step), self.min_replicas)
        with self.router._lock:
            candidates = [r.name for r in self.router.replicas
                          if r.name not in self.router._dead
                          and r.name not in self.router._cordoned]
            views = dict(self.router._views)
            shadows = dict(self.router._shadows)
        victims: list[str] = []
        for _ in range(size - target):
            if len(candidates) <= 1:
                break       # never cordon the last live replica
            victim = self.victim_policy.choose(candidates, views,
                                               shadows)
            candidates.remove(victim)
            victims.append(victim)
        if not victims:
            return self._decide(now, rec, "hold",
                                "no cordonable victim")
        for victim in victims:
            self.router.cordon_replica(victim)
            self.metrics.counter("autoscaler.cordons").inc()
            with self._lock:
                self._draining[victim] = {"since": now,
                                          "forced": False}
            self.metrics.event("autoscaler.cordon", replica=victim,
                               policy=self.victim_policy.name,
                               reason=rec.get("reason"))
        self._last_action_ts = now
        self._shrink_since = None
        self.metrics.counter("autoscaler.actions").inc()
        self.metrics.gauge("autoscaler.replicas_target").set(target)
        self.metrics.gauge("autoscaler.draining").set(
            len(self._draining))
        return self._decide(now, rec, "scale_down",
                            f"cordoned {victims}; draining",
                            replicas=victims)

    def _advance_drains(self, now: float) -> None:
        """Converge cordoned victims: retire the drained, fail open
        the stuck.  Runs every tick, enabled or not."""
        with self._lock:
            draining = list(self._draining.items())
        if not draining:
            return
        for name, info in draining:
            with self.router._lock:
                present = any(r.name == name
                              for r in self.router.replicas)
                inflight = self.router._inflight.get(name, 0)
            if not present:
                with self._lock:
                    self._draining.pop(name, None)
            elif inflight == 0:
                self._retire(name, now)
            elif (not info["forced"]
                    and now - info["since"] >= self.drain_s):
                self._fail_open(name, now)
        self.metrics.gauge("autoscaler.draining").set(
            len(self._draining))

    def _retire(self, name: str, now: float) -> None:
        try:
            self.router.retire_replica(name)
        except (KeyError, ValueError) as e:
            # Raced a concurrent removal, or the fleet shrank to one
            # under us: un-cordon rather than strand the replica.
            self.router.uncordon_replica(name)
            self.metrics.event("autoscaler.retire_abandoned",
                               replica=name, error=str(e))
            with self._lock:
                self._draining.pop(name, None)
            return
        sup = self.supervisor
        if sup is not None and hasattr(sup, "forget"):
            sup.forget(name)
        with self._lock:
            self._draining.pop(name, None)
        gen = self._bump_epoch("scale_down", now)
        self.metrics.counter("autoscaler.scale_downs").inc()
        self.metrics.event("autoscaler.retire", replica=name,
                           epoch=gen)

    def _fail_open(self, name: str, now: float) -> None:
        """A victim still busy past the drain deadline is killed
        through the crash path instead of waited on forever: every
        in-flight callback fires ``None``, the router replays each
        request on a survivor (bit-identical by greedy determinism),
        and journaled accepts stay replayable — zero drops either
        way."""
        with self._lock:
            info = self._draining.get(name)
            if info is None:
                return
            info["forced"] = True
        self.metrics.event("autoscaler.drain_force", replica=name,
                           waited_s=now - info["since"])
        try:
            handle = self.router._handle(name)
        except KeyError:
            return
        die = getattr(handle, "_die", None)
        if callable(die):
            die()       # fires every in-flight callback with None
        else:
            self.router._mark_dead(name)

    # -- bookkeeping -------------------------------------------------------

    def _fresh_name(self) -> str:
        with self.router._lock:
            taken = {r.name for r in self.router.replicas}
        while True:
            name = f"auto{self._spawn_seq}"
            self._spawn_seq += 1
            if name not in taken:
                return name

    def _bump_epoch(self, reason: str, now: float) -> int:
        with self.router._lock:
            members = [r.name for r in self.router.replicas]
        gen = self.epoch.bump(members, reason, now)
        self.metrics.gauge("autoscaler.epoch").set(gen)
        return gen

    def _decide(self, now: float, rec: dict, action: str, why: str,
                record: bool = True, **extra: Any) -> dict:
        decision = {"t": now, "action": action, "why": why,
                    "advice": {k: rec.get(k)
                               for k in ("action", "n", "reason")},
                    **extra}
        with self._lock:
            self._last_decision = decision
            if record:
                self._history.append(decision)
        if record and action == "hold":
            self.metrics.event("autoscaler.hold", why=why,
                               advice=rec.get("action"))
        return decision

    # -- export ------------------------------------------------------------

    def draining(self) -> list[str]:
        with self._lock:
            return sorted(self._draining)

    def report(self) -> dict:
        """JSON-serializable autoscaler state (the ``/autoscaler``
        payload and the ``state_dump()`` line)."""
        with self.router._lock:
            size = len(self.router.replicas)
        with self._lock:
            draining = sorted(self._draining)
            history = list(self._history)
            last_decision = self._last_decision
        last_action = None
        for d in reversed(history):
            if d["action"] != "hold":
                last_action = d
                break
        return {
            "enabled": self.enabled,
            "size": size,
            "epoch": self.epoch.snapshot(),
            "cordoned": self.router.cordoned(),
            "draining": draining,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "step": self.step,
            "cooldown_s": self.cooldown_s,
            "stable_s": self.stable_s,
            "drain_s": self.drain_s,
            "victim_policy": self.victim_policy.name,
            "last_action": last_action,
            "last_decision": last_decision,
            "history": history,
        }


def measure_autoscale_goodput(
        params: Any = None, cfg: Any = None, *,
        engines: "Sequence[Any] | None" = None,
        rate: float = 32.0, duration_s: float = 0.5,
        seed: int = 0, grow_n: int = 1,
        n_slots: int = 4, chunk: int = 16,
        max_len: "int | None" = None,
        timeout_s: float = 60.0) -> dict:
    """The ``serve_autoscale_*`` bench arm: goodput retention across a
    Bursty traffic step.

    Serves one seeded open-loop :class:`~horovod_tpu.loadgen.Bursty`
    schedule against a single-replica fleet (the pre-step baseline),
    actuates a scripted ``scale_up(grow_n)`` through the live
    :class:`FleetAutoscaler` — supervisor factory seam, epoch bump and
    all — then serves the *same* schedule again on the grown fleet.
    ``retention = goodput_post / goodput_pre`` is the headline: how
    much of the burst's SLO-good work the grow won back.  The arm ends
    with a scripted scale-down so the zero-drop cordon → drain →
    retire path runs under the bench too; ``serve_autoscale_scale_ok``
    gates on the full round trip (grew, served on the new replica,
    retired back to one, epoch advanced twice).

    Pass ``engines`` to reuse an existing fleet seed-replica list
    (tests), or ``params``/``cfg`` to build one."""
    from horovod_tpu import faults as faults_mod
    from horovod_tpu.loadgen import (DEFAULT_TENANTS, Bursty,
                                     RequestMix, build_schedule,
                                     run_open_loop, summarize_rung)
    from horovod_tpu.metrics import MetricsRegistry
    from horovod_tpu.router import RouterServer
    from horovod_tpu.serving import Request
    from horovod_tpu.supervisor import ReplicaSupervisor

    mix = RequestMix(DEFAULT_TENANTS, seed)
    reg = MetricsRegistry()
    fr = faults_mod.FaultRegistry()
    if engines is None:
        from horovod_tpu.serving_scheduler import ServeEngine
        if max_len is None:
            need = (max(t.prefix_len + t.prompt_len[1]
                        + t.new_tokens[1] for t in mix.tenants) + chunk)
            max_len = -(-need // chunk) * chunk      # block-aligned
        engines = [ServeEngine(params, cfg, n_slots=n_slots,
                               max_len=max_len, chunk=chunk,
                               prefix_cache=True, metrics=reg,
                               faults=fr)]
    for eng in engines:
        eng.run([Request(prompt=[1] * (eng.chunk + 1),
                         max_new_tokens=2)])
    router = RouterServer(engines, registry=reg, faults=fr)
    sup = ReplicaSupervisor(router, backoff_s=0.01, warm_prefixes=4)
    asc = FleetAutoscaler(router, supervisor=sup, enabled=True,
                          cooldown_s=0.0, stable_s=0.0,
                          min_replicas=1,
                          max_replicas=len(engines) + grow_n,
                          step=grow_n, drain_s=0.0, faults=fr)
    base_size = len(engines)
    sched = build_schedule(Bursty(rate, seed), mix, duration_s, seed)
    try:
        pre = summarize_rung(
            run_open_loop(router, sched, timeout_s=timeout_s),
            offered_rps=rate, duration_s=duration_s)
        grow = asc.actuate({"action": "scale_up", "n": grow_n,
                            "reason": "bench traffic step"})
        post = summarize_rung(
            run_open_loop(router, sched, timeout_s=timeout_s),
            offered_rps=rate, duration_s=duration_s)
        shrink = asc.actuate({"action": "scale_down", "n": grow_n,
                              "reason": "bench step over"})
        deadline = time.monotonic() + timeout_s
        while asc.draining() and time.monotonic() < deadline:
            router.poll_now()
            time.sleep(0.005)
        router.reap_tickets(0)
        leaked = router.memory_report()["tickets"]
        with router._lock:
            final_size = len(router.replicas)
        epoch = asc.epoch.generation
    finally:
        router.stop()
    grown = list(grow.get("replicas", []))
    scale_ok = (grow["action"] == "scale_up"
                and shrink["action"] == "scale_down"
                and final_size == base_size
                and epoch >= 2 and leaked == 0)
    retention = (post["goodput"] / pre["goodput"]
                 if pre["goodput"] > 0 else float(post["goodput"] > 0))
    return {
        "serve_autoscale_seed": seed,
        "serve_autoscale_rate_rps": rate,
        "serve_autoscale_duration_s": duration_s,
        "serve_autoscale_requests": pre["n"] + post["n"],
        "serve_autoscale_goodput_pre": pre["goodput"],
        "serve_autoscale_goodput_post": post["goodput"],
        "serve_autoscale_goodput_retention": retention,
        "serve_autoscale_p99_ttft_pre_ms": pre["p99_ttft_s"] * 1e3,
        "serve_autoscale_p99_ttft_post_ms": post["p99_ttft_s"] * 1e3,
        "serve_autoscale_grown_replicas": grown,
        "serve_autoscale_final_replicas": final_size,
        "serve_autoscale_epoch": epoch,
        "serve_autoscale_scale_ok": scale_ok,
    }


def maybe_autoscaler(router: Any) -> "FleetAutoscaler | None":
    """A :class:`FleetAutoscaler` per the env contract: needs
    ``HVD_TPU_AUTOSCALE`` truthy AND a capacity advisor on the router
    (i.e. a live sampler).  Mirrors ``maybe_sampler``/``maybe_alerts``
    — unset means off, silently."""
    if os.environ.get("HVD_TPU_AUTOSCALE", "0") in ("", "0"):
        return None
    if getattr(router, "advisor", None) is None:
        return None
    return FleetAutoscaler(router, enabled=True)
