"""Autotuner — online search over fusion threshold and cycle time.

The reference generation of Horovod (0.15.1) exposes
``HOROVOD_FUSION_THRESHOLD`` / ``HOROVOD_CYCLE_TIME`` as static knobs the
user must hand-tune per model (reference horovod/common/operations.cc:
1614-1685); the project's later releases grew ``HOROVOD_AUTOTUNE``, a
background search that adjusts both while training runs.  This is the
TPU-native equivalent for the eager engine: a **coordinate-descent hill
climber** over a log-spaced threshold grid and a cycle-time grid, scored by
observed wire throughput.

Why hill-climbing and not Bayesian optimization: the search space here is a
tiny 2-D grid (the compiled SPMD path doesn't need tuning at all — XLA owns
fusion there), samples are cheap (every flush is one), and a monotone
hill climber is deterministic and explainable in the autotune log.

Mechanics: the engine calls :meth:`Autotuner.observe` after each flush that
dispatched at least one fused allreduce, passing the per-rank bytes moved
and one output array of the batch.  Samples accumulate into a window; when
the window closes (enough flushes AND enough bytes), the autotuner blocks
on that output array — making the window's wall-clock cover actual device
completion, not just async dispatch — scores the current setting in
bytes/sec, writes a log row, and either moves to a neighboring setting or,
once no neighbor beats the incumbent, pins the best setting and stops.

The score is **end-to-end cadence**, deliberately: the window's wall-clock
spans inter-flush training compute, so ``score_bytes_per_sec`` measures how
fast the whole train loop drains gradient traffic under a setting — the
objective the user actually cares about — not isolated wire throughput.
(An overlap-friendly setting that slows raw wire rate but hides it under
compute SHOULD win.)  Before pinning a winner the tuner re-scores it once
(a confirmation revisit); revisited settings average their samples rather
than keeping the best, so one lucky noisy window can't entrench an
incumbent — if the refreshed average drops below a neighbor, the search
resumes from the new best.

Enable with ``HOROVOD_AUTOTUNE=1``; ``HOROVOD_AUTOTUNE_LOG=<file>`` writes
a CSV of (setting, score) rows — both knob names shared with later
Horovod so launch scripts carry over.
"""

from __future__ import annotations

import sys
import time
from typing import Any

MiB = 1024 * 1024

# Log-spaced search grids.  0 disables fusion entirely (every tensor its
# own collective) — a real candidate: for large-tensor workloads fusion
# only adds concat latency.
THRESHOLD_GRID = (0, 1 * MiB, 4 * MiB, 16 * MiB, 64 * MiB, 256 * MiB)
CYCLE_GRID_MS = (1.0, 2.5, 5.0, 10.0, 25.0)


class Autotuner:
    """Coordinate-descent over (fusion_threshold, cycle_time).

    Owns no threads: driven entirely by ``observe()`` calls from the
    engine's cycle thread, and mutates ``config`` in place (the engine
    reads both knobs from config on every flush/tick).
    """

    def __init__(self, config, *, warmup_samples: int = 3,
                 window_flushes: int = 10, min_window_bytes: int = 1 * MiB,
                 log_path: str | None = None, on_move=None):
        """``on_move(threshold_bytes, cycle_ms)`` fires on every setting
        move (including the final pin) — the control-plane hook: with the
        native controller, rank 0's engine forwards it to
        ``NativeController.set_tuned`` so the whole gang re-buckets at the
        next tick and every rank observes the move via the response
        piggyback."""
        import threading

        self.config = config
        self.on_move = on_move
        self.warmup_samples = warmup_samples
        self.warmup_left = warmup_samples
        self.window_flushes = window_flushes
        self.min_window_bytes = min_window_bytes
        self.log_path = log_path
        self.done = False
        # observe() is called outside the engine's flush lock (so the
        # device-completion probe can't stall concurrent flushes); guard
        # the tuner's own state against concurrent flush threads instead.
        self._obs_lock = threading.Lock()

        ti = _nearest(THRESHOLD_GRID, config.fusion_threshold_bytes)
        ci = _nearest(CYCLE_GRID_MS, config.cycle_time_ms)
        self._pos = (ti, ci)
        self._scores: dict[tuple[int, int], float] = {}
        self._score_counts: dict[tuple[int, int], int] = {}
        self._pending: list[tuple[int, int]] = []
        self._coord = 0            # 0: tune threshold, 1: tune cycle time
        self._stale_coords = 0     # coords in a row with no improvement
        self._confirmed = False    # incumbent re-scored before finishing?
        self._best_seen: tuple[int, int] | None = None  # confirm target
        # Bound on confirmation revisits: two settings with statistically
        # equal means could otherwise flip the argmax forever, each flip
        # paying a warmup + scoring window.  After the budget is spent the
        # tuner pins whatever is best — the candidates are equivalent
        # anyway, that's WHY they keep flipping.
        self._confirm_budget = 3
        self._win_bytes = 0
        self._win_flushes = 0
        self._win_t0: float | None = None
        self._win_last_out: Any = None
        if self.log_path:
            with open(self.log_path, "w") as f:
                f.write("threshold_bytes,cycle_time_ms,score_bytes_per_sec,best\n")

    # ------------------------------------------------------------------ engine

    def observe(self, nbytes: int, last_out: Any) -> None:
        """One flush's worth of dispatched allreduce traffic."""
        if self.done or nbytes <= 0:
            return
        with self._obs_lock:
            if self.warmup_left > 0:   # discard compile-dominated flushes
                self.warmup_left -= 1
                return
            if self._win_t0 is None:
                self._win_t0 = time.monotonic()
            self._win_bytes += nbytes
            self._win_flushes += 1
            self._win_last_out = last_out
            if (self._win_flushes >= self.window_flushes
                    and self._win_bytes >= self.min_window_bytes):
                self._close_window()

    # ------------------------------------------------------------------ search

    def _close_window(self) -> None:
        import jax

        if self._win_last_out is not None:
            try:
                jax.block_until_ready(self._win_last_out)
            except Exception:
                pass
        elapsed = max(time.monotonic() - self._win_t0, 1e-9)
        score = self._win_bytes / elapsed
        # Running mean per setting: repeated visits refine the estimate
        # instead of max() locking in one lucky noisy sample.
        k = self._score_counts.get(self._pos, 0)
        prev = self._scores.get(self._pos, 0.0)
        self._scores[self._pos] = (prev * k + score) / (k + 1)
        self._score_counts[self._pos] = k + 1
        self._log_row(score)
        self._win_bytes = 0
        self._win_flushes = 0
        self._win_t0 = None
        self._win_last_out = None
        self._advance()

    def _advance(self) -> None:
        if not self._pending:
            # Current coordinate swept?  Candidates = unvisited neighbors of
            # the best point along the active coordinate.
            best = max(self._scores, key=self._scores.__getitem__)
            if best != self._best_seen:
                # The incumbent changed identity (first convergence, or a
                # confirmation revisit demoted the old winner): whoever is
                # best now must be (re-)confirmed before being pinned, even
                # when it has no unexplored neighbors left.
                self._best_seen = best
                self._confirmed = False
            grid = THRESHOLD_GRID if self._coord == 0 else CYCLE_GRID_MS
            i = best[self._coord]
            neighbors = [
                _with_coord(best, self._coord, j)
                for j in (i - 1, i + 1)
                if 0 <= j < len(grid)
            ]
            self._pending = [p for p in neighbors if p not in self._scores]
            if not self._pending:
                # No unexplored neighbor on this coordinate: switch, and if
                # BOTH coordinates are locally optimal, finish.
                self._stale_coords += 1
                self._coord ^= 1
                if self._stale_coords >= 2:
                    if not self._confirmed and self._confirm_budget > 0:
                        self._confirm_budget -= 1
                        # Confirmation revisit: score the incumbent a second
                        # time and AVERAGE with its earlier sample(s) (see
                        # _close_window) before pinning it, so a single
                        # noisy window can't entrench a winner.  If the
                        # refreshed mean falls below a neighbor, the next
                        # _advance() resumes from that new best.
                        self._confirmed = True
                        self._move_to(best)
                        return
                    self._finish(max(self._scores,
                                     key=self._scores.__getitem__))
                    return
                self._advance()
                return
            self._stale_coords = 0
            # New unexplored settings queued: whatever wins later must be
            # (re-)confirmed before the search pins it.
            self._confirmed = False
        self._move_to(self._pending.pop(0))

    def _move_to(self, pos: tuple[int, int]) -> None:
        self._pos = pos
        self.config.fusion_threshold_bytes = THRESHOLD_GRID[pos[0]]
        self.config.cycle_time_ms = CYCLE_GRID_MS[pos[1]]
        if self.on_move is not None:
            self.on_move(THRESHOLD_GRID[pos[0]], CYCLE_GRID_MS[pos[1]])
        # A new threshold changes bucket shapes → the next flushes pay XLA
        # compilation.  Each grid point is scored exactly once, so letting
        # compile time into its one window would permanently penalize every
        # newly-visited setting; re-warm after every move.
        self.warmup_left = self.warmup_samples

    def _finish(self, best: tuple[int, int]) -> None:
        self._move_to(best)
        self.done = True
        self._log_row(self._scores[best], best=True)
        print(
            "horovod_tpu autotune converged: "
            f"HOROVOD_FUSION_THRESHOLD={THRESHOLD_GRID[best[0]]} "
            f"HOROVOD_CYCLE_TIME={CYCLE_GRID_MS[best[1]]} "
            f"({self._scores[best] / MiB:.1f} MiB/s observed)",
            file=sys.stderr,
        )

    def _log_row(self, score: float, best: bool = False) -> None:
        if not self.log_path:
            return
        with open(self.log_path, "a") as f:
            f.write(
                f"{self.config.fusion_threshold_bytes},"
                f"{self.config.cycle_time_ms},{score:.1f},"
                f"{int(best)}\n"
            )


def _nearest(grid, value) -> int:
    return min(range(len(grid)), key=lambda i: abs(grid[i] - value))


def _with_coord(pos: tuple[int, int], coord: int, j: int) -> tuple[int, int]:
    return (j, pos[1]) if coord == 0 else (pos[0], j)
