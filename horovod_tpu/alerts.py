"""Declarative alerting and capacity advice over the sampled series.

The :class:`~horovod_tpu.timeseries.MetricsSampler` remembers; this
module judges.  ``ALERT_RULES`` is the canonical rule table — a pure
literal, like ``METRIC_HELP`` and ``ENV_KNOBS``, so hvdlint extracts
it by AST ``literal_eval`` without importing the package (HVD006
checks every rule references a registered metric name and is asserted
somewhere under ``tests/``), and the docs table in
``docs/observability.md`` is rendered from it
(``python -m horovod_tpu.alerts``).

Rule kinds (the ``kind`` field picks the evaluator):

* ``burn_rate`` — the SRE-workbook multi-window method on the
  ``serve.goodput`` gauge (itself ``SLOWindow.goodput()`` from
  ``slo_report()``): the error-budget burn ``(1 - goodput) /
  (1 - objective)`` must exceed the threshold over BOTH the short and
  the long window before firing — the short window gives fast reset,
  the long window rejects blips.
* ``drift`` — a histogram's recent p99 against its own trailing
  baseline (the window just *before* the recent one), ratio-gated
  with an absolute floor so microsecond noise can't page.
* ``slope`` — least-squares slope of a gauge; fires when the
  projected time-to-zero falls inside the horizon (free-KV
  exhaustion).
* ``threshold`` — windowed mean of a gauge above a line (straggler
  skew).
* ``delta`` — a counter's windowed increment at or above a line
  (replica deaths, supervisor respawn flapping).

Every rule runs a firing/pending/resolved state machine with
hysteresis (``pending_s`` of sustained truth to fire, ``clear_s`` of
sustained falsehood to resolve) and dedup (a firing rule never
re-emits).  Transitions are stamped into the structured event log
(``alert.pending`` / ``alert.fire`` / ``alert.resolve`` /
``alert.cancel`` kinds) and onto ``alert.*`` counters.  A rule whose
metric has no samples in the window is *no-data*: it holds its current
state rather than flapping — a torn snapshot or a missing rank
degrades freshness, not correctness.

``time_scale`` multiplies every ``*_s`` rule parameter, so chaos
campaigns evaluate production-shaped rules against compressed
wall-clock storms without a parallel rule table.

:class:`CapacityAdvisor` folds the live series with the last
``serve_load_report.json`` knee (PR 11) into ``scale_up(n)`` /
``scale_down(n)`` / ``hold`` recommendation records with the evidence
attached — the exact input the PR-13 autoscaler will wire to the
PR-10 supervisor actuators.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Callable, Iterable, Sequence

from horovod_tpu import metrics as metrics_mod
from horovod_tpu import timeseries as timeseries_mod
from horovod_tpu.monitor import env_float

# The canonical alert-rule table.  MUST stay a pure literal (hvdlint
# HVD006 extracts it by literal_eval; the docs table is rendered from
# it).  Every ``*_s`` field is in seconds and scales by the manager's
# ``time_scale``; ``pending_s`` 0 fires the moment the condition holds.
ALERT_RULES = (
    {"name": "goodput_burn_fast", "severity": "page",
     "kind": "burn_rate", "metric": "serve.goodput",
     "objective": 0.99, "burn": 10.0, "short_s": 30.0, "long_s": 300.0,
     "pending_s": 0.0, "clear_s": 60.0,
     "help": "Error budget burning >= 10x sustained over 30 s AND 5 m "
             "-- the fast page of the multi-window SLO pair."},
    {"name": "goodput_burn_slow", "severity": "ticket",
     "kind": "burn_rate", "metric": "serve.goodput",
     "objective": 0.99, "burn": 2.0, "short_s": 300.0, "long_s": 1800.0,
     "pending_s": 60.0, "clear_s": 300.0,
     "help": "Error budget burning >= 2x over 5 m AND 30 m -- the "
             "slow-leak ticket of the multi-window SLO pair."},
    {"name": "ttft_p99_drift", "severity": "ticket",
     "kind": "drift", "metric": "serve.ttft_s", "q": 0.99,
     "recent_s": 60.0, "baseline_s": 600.0, "ratio": 2.0,
     "floor": 0.001, "pending_s": 30.0, "clear_s": 120.0,
     "help": "Recent p99 TTFT at least 2x the trailing 10 m baseline "
             "(and above a 1 ms floor)."},
    {"name": "kv_exhaustion", "severity": "page",
     "kind": "slope", "metric": "kv.free_blocks",
     "window_s": 120.0, "horizon_s": 300.0,
     "pending_s": 0.0, "clear_s": 60.0,
     "help": "Free KV blocks trending to zero within 5 m at the "
             "current 2 m slope."},
    {"name": "straggler_skew", "severity": "ticket",
     "kind": "threshold", "metric": "hvd.step_skew_s",
     "above": 1.0, "window_s": 60.0,
     "pending_s": 30.0, "clear_s": 60.0,
     "help": "Mean slowest-minus-median rank step skew above 1 s "
             "over the last minute."},
    {"name": "replica_death", "severity": "page",
     "kind": "delta", "metric": "router.replica_deaths",
     "min_delta": 1.0, "window_s": 60.0,
     "pending_s": 0.0, "clear_s": 60.0,
     "help": "A replica transitioned healthy->dead within the last "
             "minute."},
    {"name": "replica_flap", "severity": "page",
     "kind": "delta", "metric": "supervisor.respawns",
     "min_delta": 3.0, "window_s": 300.0,
     "pending_s": 0.0, "clear_s": 300.0,
     "help": "Three or more supervisor respawns inside 5 m -- the "
             "fleet is flapping, not healing."},
    {"name": "autoscaler_flap", "severity": "ticket",
     "kind": "delta", "metric": "autoscaler.actions",
     "min_delta": 3.0, "window_s": 600.0,
     "pending_s": 0.0, "clear_s": 300.0,
     "help": "Three or more autoscaler actuations inside 10 m -- the "
             "fleet is resizing faster than demand can justify."},
    {"name": "device_hbm_exhaustion", "severity": "page",
     "kind": "threshold", "metric": "device.hbm_used_fraction",
     "above": 0.92, "window_s": 30.0,
     "pending_s": 10.0, "clear_s": 60.0,
     "help": "Device HBM use above 92% of bytes_limit sustained over "
             "30 s -- the next allocation spike OOMs the replica. "
             "Needs the device telemetry plane; CPU backends report "
             "no memory_stats, so the series is absent and the rule "
             "holds state."},
)


def rule_names() -> tuple[str, ...]:
    return tuple(r["name"] for r in ALERT_RULES)


def render_alert_table(rules: Sequence[dict] = ALERT_RULES) -> str:
    """The docs/observability.md alert table (paste verbatim on
    drift; regenerate with ``python -m horovod_tpu.alerts``)."""
    lines = ["| Rule | Severity | Kind | Metric | Fire / clear | "
             "Meaning |", "| --- | --- | --- | --- | --- | --- |"]
    for r in rules:
        windows = ", ".join(
            f"{k}={r[k]:g}" for k in sorted(r)
            if k.endswith("_s") and k not in ("pending_s", "clear_s"))
        gate = (f"{windows}; pending {r['pending_s']:g} s / "
                f"clear {r['clear_s']:g} s")
        lines.append(
            f"| `{r['name']}` | {r['severity']} | `{r['kind']}` | "
            f"`{r['metric']}` | {gate} | {r['help']} |")
    return "\n".join(lines)


class AlertManager:
    """Evaluates ``ALERT_RULES`` over a sampler's series on ``tick()``.

    Ticked from the same loops as the sampler (engine step / router
    poll) — no threads.  ``eval_s`` gates evaluation cadence (default:
    the sampler's cadence); ``time_scale`` compresses every rule
    window for accelerated tests and chaos campaigns.
    """

    _GUARDED_BY_LOCK = ("_states", "_history", "_last_eval")

    def __init__(self, sampler: timeseries_mod.MetricsSampler, *,
                 rules: Sequence[dict] = ALERT_RULES,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 eval_s: float | None = None, time_scale: float = 1.0,
                 history: int = 256,
                 clock: Callable[[], float] | None = None):
        self.sampler = sampler
        self.registry = (registry if registry is not None
                         else sampler.registry)
        self.rules = tuple(rules)
        self.eval_s = (eval_s if eval_s is not None
                       else sampler.sample_s)
        self.time_scale = time_scale
        self.clock = clock if clock is not None else sampler.clock
        self._lock = threading.Lock()
        self._states: dict[str, dict] = {
            r["name"]: {"state": "ok", "since": None, "last_true": None,
                        "value": None, "no_data": True,
                        "ever_true": False, "fired": 0, "resolved": 0}
            for r in self.rules}
        self._history: collections.deque[dict] = collections.deque(
            maxlen=history)
        self._last_eval = float("-inf")
        self._fired = self.registry.counter("alert.fired")
        self._resolved_c = self.registry.counter("alert.resolved")
        self._evals = self.registry.counter("alert.evals")
        self._firing_g = self.registry.gauge("alert.firing")
        self._pending_g = self.registry.gauge("alert.pending")

    def _s(self, rule: dict, key: str) -> float:
        return float(rule[key]) * self.time_scale

    # -- evaluation --------------------------------------------------------

    def tick(self, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        if now - self._last_eval < self.eval_s:
            return False
        self.evaluate(now)
        return True

    def evaluate(self, now: float | None = None) -> list[dict]:
        """Run every rule's condition and state machine; returns the
        transitions emitted this pass."""
        now = self.clock() if now is None else now
        transitions: list[dict] = []
        with self._lock:
            self._last_eval = now
            for rule in self.rules:
                cond, value = self._condition(rule, now)
                st = self._states[rule["name"]]
                st["no_data"] = cond is None
                if cond is None:
                    continue                   # hold state on no-data
                st["value"] = value
                if cond:
                    st["ever_true"] = True
                    st["last_true"] = now
                tr = self._advance(rule, st, cond, now)
                if tr is not None:
                    transitions.append(tr)
            firing = sum(1 for s in self._states.values()
                         if s["state"] == "firing")
            pending = sum(1 for s in self._states.values()
                          if s["state"] == "pending")
        self._evals.inc()
        self._firing_g.set(firing)
        self._pending_g.set(pending)
        for tr in transitions:
            if tr["to"] == "firing":
                self._fired.inc()
            elif tr["from"] == "firing":
                self._resolved_c.inc()
            self.registry.event(
                "alert." + tr["event"], rule=tr["rule"],
                severity=tr["severity"], state=tr["to"],
                value=tr["value"])
        return transitions

    def _advance(self, rule: dict, st: dict, cond: bool,
                 now: float) -> dict | None:
        state = st["state"]
        if state == "ok":
            if not cond:
                return None
            if self._s(rule, "pending_s") <= 0:
                return self._to_locked(rule, st, "firing", "fire", now)
            return self._to_locked(rule, st, "pending", "pending", now)
        if state == "pending":
            if not cond:
                return self._to_locked(rule, st, "ok", "cancel", now)
            if now - st["since"] >= self._s(rule, "pending_s"):
                return self._to_locked(rule, st, "firing", "fire", now)
            return None
        # firing: dedup — only the resolve transition emits.
        if cond:
            return None
        if (st["last_true"] is None
                or now - st["last_true"] >= self._s(rule, "clear_s")):
            return self._to_locked(rule, st, "ok", "resolve", now)
        return None

    def _to_locked(self, rule: dict, st: dict, to: str, event: str,
            now: float) -> dict:
        tr = {"t": now, "rule": rule["name"],
              "severity": rule["severity"], "from": st["state"],
              "to": to, "event": event, "value": st["value"]}
        st["state"] = to
        st["since"] = now
        if to == "firing":
            st["fired"] += 1
        elif event == "resolve":
            st["resolved"] += 1
        self._history.append(tr)
        return tr

    # -- rule conditions ---------------------------------------------------

    def _condition(self, rule: dict,
                   now: float) -> tuple[bool | None, Any]:
        """(condition, value) — condition None means no data."""
        kind = rule["kind"]
        s = self.sampler
        name = rule["metric"]
        if kind == "burn_rate":
            burns = []
            for key in ("short_s", "long_s"):
                g = s.gauge_stats(name, self._s(rule, key), now=now)
                if g["n"] == 0:
                    return None, None
                burns.append((1.0 - g["mean"])
                             / max(1.0 - rule["objective"], 1e-9))
            value = min(burns)
            return value >= rule["burn"], value
        if kind == "drift":
            recent_s = self._s(rule, "recent_s")
            cur = s.hist_percentile(name, recent_s, rule["q"], now=now)
            base = s.hist_percentile(
                name, self._s(rule, "baseline_s"), rule["q"],
                now=now, end_offset_s=recent_s)
            if cur is None or base is None:
                return None, None
            value = cur / base if base > 0 else math.inf
            return (cur >= rule["floor"]
                    and value >= rule["ratio"]), value
        if kind == "slope":
            window_s = self._s(rule, "window_s")
            slope = s.slope_per_s(name, window_s, now=now)
            if slope is None:
                return None, None
            if slope >= 0:
                return False, math.inf
            last = s.gauge_stats(name, window_s, now=now)["last"]
            tto = max(last, 0.0) / -slope
            return tto <= self._s(rule, "horizon_s"), tto
        if kind == "threshold":
            g = s.gauge_stats(name, self._s(rule, "window_s"), now=now)
            if g["n"] == 0:
                return None, None
            return g["mean"] > rule["above"], g["mean"]
        if kind == "delta":
            c = s.counter_rate(name, self._s(rule, "window_s"), now=now)
            if c["n"] == 0:
                return None, None
            return c["delta"] >= rule["min_delta"], c["delta"]
        return None, None

    # -- export ------------------------------------------------------------

    def firing(self) -> list[str]:
        with self._lock:
            return sorted(n for n, s in self._states.items()
                          if s["state"] == "firing")

    def states(self) -> dict[str, dict]:
        with self._lock:
            return {n: dict(s) for n, s in self._states.items()}

    def report(self) -> dict:
        """JSON-serializable alert state (the ``/alerts`` payload and
        the ``alerts`` section of ``metrics_snapshot()``)."""
        with self._lock:
            rules = []
            for r in self.rules:
                st = self._states[r["name"]]
                rules.append(dict(r, state=st["state"],
                                  since=st["since"],
                                  value=st["value"],
                                  no_data=st["no_data"],
                                  fired=st["fired"],
                                  resolved=st["resolved"]))
            return {
                "time_scale": self.time_scale,
                "eval_s": self.eval_s,
                "firing": sorted(n for n, s in self._states.items()
                                 if s["state"] == "firing"),
                "pending": sorted(n for n, s in self._states.items()
                                  if s["state"] == "pending"),
                "rules": rules,
                "history": list(self._history),
            }


class CapacityAdvisor:
    """Folds live series and the load-test knee into a scaling record.

    ``recommend()`` returns ``{"action": "scale_up" | "scale_down" |
    "hold", "n": int, "reason": str, "evidence": {...}, "t": float}``.
    Evidence carries every input the decision read, so the PR-13
    autoscaler (and a human reading ``state_dump()``) can audit it.

    The knee comes from the last ``serve_load_report.json`` the bench
    wrote (PR 11) — per-replica sustainable goodput RPS.  Without a
    report the advisor still works from goodput, queue growth, and
    free-KV slope; it just can't size ``n`` from demand.
    """

    def __init__(self, sampler: timeseries_mod.MetricsSampler, *,
                 alerts: AlertManager | None = None,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 load_report: dict | str | None = None,
                 window_s: float = 60.0, objective: float = 0.99,
                 headroom: float = 0.8, low_util: float = 0.3,
                 time_scale: float = 1.0, history: int = 64,
                 clock: Callable[[], float] | None = None):
        self.sampler = sampler
        self.alerts = alerts
        self.registry = (registry if registry is not None
                         else sampler.registry)
        self._load_report = load_report
        self.window_s = window_s * time_scale
        self.objective = objective
        self.headroom = headroom
        self.low_util = low_util
        self.clock = clock if clock is not None else sampler.clock
        self._lock = threading.Lock()
        self._history: collections.deque[dict] = collections.deque(
            maxlen=history)
        self._recs = self.registry.counter("advisor.recommendations")
        self._delta_g = self.registry.gauge("advisor.target_delta")

    def load_knee(self) -> dict | None:
        """The knee row from the configured load report: explicit dict,
        a path, or the bench's default drop location."""
        src = self._load_report
        if isinstance(src, dict):
            return src
        path = src
        if path is None:
            path = os.path.join(
                os.environ.get("HVD_TPU_BENCH_CACHE") or ".",
                "serve_load_report.json")
        try:
            with open(path) as f:
                r = json.load(f)
        except (OSError, ValueError):
            return None
        return r if isinstance(r, dict) else None

    def recommend(self, now: float | None = None) -> dict:
        now = self.clock() if now is None else now
        s = self.sampler
        w = self.window_s
        goodput = s.gauge_stats("serve.goodput", w, now=now)
        replicas = s.gauge_stats("router.replicas_healthy", w,
                                 now=now)
        queue = s.slope_per_s("serve.queue_depth", w, now=now)
        kv_slope = s.slope_per_s("kv.free_blocks", w, now=now)
        done = s.counter_rate("serve.requests_completed", w, now=now)
        knee_report = self.load_knee()
        knee = None
        if knee_report:
            knee = knee_report.get("serve_load_knee_goodput_rps")
        n_replicas = int(replicas["last"]) if replicas["n"] else 1
        n_replicas = max(n_replicas, 1)
        firing = self.alerts.firing() if self.alerts else []
        evidence = {
            "goodput_mean": goodput["mean"] if goodput["n"] else None,
            "replicas_healthy": n_replicas,
            "queue_depth_slope": queue,
            "kv_free_blocks_slope": kv_slope,
            "completed_rps": done["rate"],
            "knee_goodput_rps": knee,
            "firing": firing,
            "window_s": w,
            "objective": self.objective,
            "headroom": self.headroom,
        }
        action, n, reason = self._decide(goodput, queue, kv_slope,
                                         done, knee, n_replicas,
                                         firing)
        rec = {"action": action, "n": n, "reason": reason,
               "evidence": evidence, "t": now}
        with self._lock:
            self._history.append(rec)
        self._recs.inc()
        self._delta_g.set(n if action == "scale_up"
                          else -n if action == "scale_down" else 0)
        return rec

    def _decide(self, goodput, queue, kv_slope, done, knee,
                n_replicas, firing) -> tuple[str, int, str]:
        if goodput["n"] == 0:
            return "hold", 0, "no goodput samples in window"
        sagging = goodput["mean"] < self.objective
        backlog = queue is not None and queue > 0
        draining_kv = kv_slope is not None and kv_slope < 0
        if sagging and (backlog or draining_kv or firing):
            n = 1
            if knee and knee > 0:
                # Demand-sized: replicas needed to serve the observed
                # completion rate at knee-with-headroom per replica.
                need = math.ceil(done["rate"]
                                 / (knee * self.headroom))
                n = max(need - n_replicas, 1)
            why = []
            if backlog:
                why.append("queue growing")
            if draining_kv:
                why.append("free KV draining")
            if firing:
                why.append("alerts firing: " + ",".join(firing))
            return ("scale_up", n,
                    f"goodput {goodput['mean']:.3f} < "
                    f"{self.objective:g} with " + "; ".join(why))
        if (not sagging and not firing and not backlog
                and n_replicas > 1 and knee and knee > 0
                and done["rate"] < knee * self.low_util
                * (n_replicas - 1)):
            # Demand-sized like scale_up: replicas the observed rate
            # actually needs at knee-with-headroom, never shrinking
            # past one survivor.
            need = max(math.ceil(done["rate"]
                                 / (knee * self.headroom)), 1)
            n = max(min(n_replicas - need, n_replicas - 1), 1)
            return ("scale_down", n,
                    f"goodput ok and {done['rate']:.2f} rps fits "
                    f"{need} replica(s) at {self.headroom:g} of "
                    f"{knee:g} rps knee")
        return "hold", 0, "within envelope"

    def report(self) -> dict:
        """Last recommendation plus bounded history (the ``/advice``
        payload renders ``recommend()`` fresh; this is the audit
        trail)."""
        with self._lock:
            hist = list(self._history)
        return {"window_s": self.window_s,
                "objective": self.objective,
                "last": hist[-1] if hist else None,
                "history": hist}


def maybe_alerts(sampler: timeseries_mod.MetricsSampler | None,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 ) -> AlertManager | None:
    """An :class:`AlertManager` per the env contract: needs a live
    sampler, and ``HVD_TPU_ALERTS`` (default on) not \"0\"."""
    if sampler is None:
        return None
    if os.environ.get("HVD_TPU_ALERTS", "1") == "0":
        return None
    return AlertManager(sampler, registry=registry)


if __name__ == "__main__":
    print(render_alert_table())
