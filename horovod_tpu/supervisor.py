"""Self-healing for the router fleet: respawn dead replicas under a
restart budget.

PR 9's :class:`~horovod_tpu.router.RouterServer` already *survives*
replica death — in-flight requests replay onto survivors, and an HTTP
replica rejoins when its probes turn healthy — but it cannot *heal*:
a dead :class:`~horovod_tpu.router.LocalReplica` (pump thread gone,
``can_revive=False``) is permanently lost, so every local death
shrinks the fleet forever.  The :class:`ReplicaSupervisor` closes that
asymmetry.  It rides the router's existing poll pass
(:meth:`~horovod_tpu.router.RouterServer.poll_now` ticks it), and for
each dead replica:

1. **Backoff** — a respawn is attempted only after an exponential
   delay (``HVD_TPU_SUPERVISE_BACKOFF_S`` base, doubling per restart),
   so a replica that dies instantly on arrival doesn't hot-loop the
   supervisor.
2. **Budget / circuit-breaker** — after
   ``HVD_TPU_SUPERVISE_MAX_RESTARTS`` respawns the replica is
   circuit-broken to **permanent-dead** (``supervisor.permanent_deaths``)
   and never retried: a replica that keeps dying is a bug, not a blip,
   and respawning it forever would mask the bug while burning compute.
3. **Respawn** — a factory builds a replacement handle.  The default
   factory for a local replica is :func:`clone_engine`: a fresh
   :class:`~horovod_tpu.serving_scheduler.ServeEngine` with the dead
   engine's exact configuration (same params/geometry/policy — greedy
   determinism then guarantees the replacement produces bit-identical
   tokens for any replayed request).  A factory may return ``None`` to
   signal *out-of-band* respawn (e.g. relaunching a remote process
   behind an :class:`~horovod_tpu.router.HttpReplica` — the handle
   itself is still valid and revives through probes); the attempt
   still consumes budget.
4. **Warm-up** — before the replacement joins routing, the supervisor
   optionally replays the hottest recently-routed prompts (the ones
   the router's own :class:`~horovod_tpu.router.ShadowPrefixIndex`
   says were cached) through the fresh engine, so the respawned
   replica re-enters prefix-affinity routing warm instead of serving
   its first real requests from a cold radix.
5. **Commit** — :meth:`~horovod_tpu.router.RouterServer.replace_replica`
   swaps the handle in under the router lock and returns the name to
   the candidate set.

Every respawn attempt checks the ``serve.supervisor`` fault site
(key = replica name) first: a firing rule fails the attempt, burning
one unit of budget and advancing the backoff — which is exactly how
the chaos campaign proves the circuit-breaker works.

The supervisor holds no thread of its own and takes no router lock
itself; it is called from the poller (or directly from tests via
:meth:`tick`), and all its state lives behind its own small lock.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Sequence

from horovod_tpu import faults as faults_mod
from horovod_tpu.monitor import env_float
from horovod_tpu.router import LocalReplica, ReplicaHandle, RouterServer
from horovod_tpu.serving import Request


def clone_engine(eng: Any) -> Any:
    """A fresh :class:`~horovod_tpu.serving_scheduler.ServeEngine`
    with ``eng``'s exact configuration: same params/config/geometry/
    policy/faults/metrics, empty state.  Greedy determinism makes the
    clone token-identical to the original for any request, which is
    what lets a respawned replica transparently serve replays."""
    from horovod_tpu.serving_scheduler import ServeEngine

    return ServeEngine(
        eng.params, eng.cfg,
        n_slots=eng.n_slots, max_len=eng.max_len, chunk=eng.chunk,
        block_size=eng.block_size,
        # The paged cache's axis-1 extent IS n_blocks (trash block
        # included), so the clone's KV geometry matches bit-for-bit.
        n_blocks=int(eng.pcache.k.shape[1]),
        tp_size=eng.tp_size,
        timeline=eng.timeline,
        preempt_after=eng.preempt_after,
        max_retries=eng.max_retries,
        watchdog_steps=eng.watchdog_steps,
        faults=eng.faults,
        metrics=eng.metrics,
        prefix_cache=eng.prefix is not None,
        monitor=False,
        slo_window=eng.slo._traces.maxlen,
        slo_e2e_s=eng.slo.slo_e2e_s,
        profile=eng.prof is not None,
        spec=eng.spec,
        draft_k=eng.draft_k,
        policy=eng.policy,
    )


class _ReplicaRecord:
    """Per-replica supervision state (guarded by the supervisor lock)."""

    __slots__ = ("restarts", "next_ts", "permanent_dead", "history")

    def __init__(self) -> None:
        self.restarts = 0               # respawn attempts consumed
        self.next_ts = 0.0              # monotonic: earliest next try
        self.permanent_dead = False     # circuit-broken
        self.history: list[dict] = []   # [{"ok": bool, "error": ...}]


class ReplicaSupervisor:
    """Respawns dead replicas for one router; see the module docstring.

    ``factories`` maps replica name → zero-arg callable returning a
    replacement :class:`~horovod_tpu.router.ReplicaHandle` (or ``None``
    for out-of-band respawn).  Replicas without a factory get the
    default: local replicas are cloned via :func:`clone_engine`;
    anything else (HTTP replicas already revive through probes) is left
    alone entirely — no budget, no backoff.

    ``warm_prefixes`` bounds how many recently-routed prompts are
    replayed into a fresh local engine before it rejoins (0 = cold
    respawn).  The candidate prompts come from the supervisor's own
    bounded ring, fed by the router's ``on_route`` hook; only prompts
    the dead replica's shadow index recognises are replayed
    (``supervisor.warm_prefixes`` counts them).
    """

    _GUARDED_BY_LOCK = ("_records", "_recent")

    def __init__(self, router: RouterServer, *,
                 max_restarts: int | None = None,
                 backoff_s: float | None = None,
                 factories: "dict[str, Callable[[], ReplicaHandle | None]] | None" = None,  # noqa: E501
                 warm_prefixes: int = 8,
                 recent_prompts: int = 64,
                 faults: "faults_mod.FaultRegistry | None" = None,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.max_restarts = int(
            max_restarts if max_restarts is not None else
            env_float("HVD_TPU_SUPERVISE_MAX_RESTARTS", 3))
        self.backoff_s = (
            backoff_s if backoff_s is not None else
            env_float("HVD_TPU_SUPERVISE_BACKOFF_S", 0.5))
        self.factories = dict(factories or {})
        self.warm_prefixes = warm_prefixes
        self.faults = faults if faults is not None else router.faults
        self.metrics = router.metrics
        self.clock = clock
        self._lock = threading.Lock()
        self._records: dict[str, _ReplicaRecord] = {}
        # Recently routed prompts, newest last — the warm-up feed.
        self._recent: collections.deque = collections.deque(
            maxlen=max(recent_prompts, 1))
        # Registered up front (literal names — the HVD005 contract).
        self.metrics.counter("supervisor.respawns")
        self.metrics.counter("supervisor.respawn_failures")
        self.metrics.counter("supervisor.permanent_deaths")
        self.metrics.counter("supervisor.warm_prefixes")
        router.supervisor = self
        if router.on_route is None:
            router.on_route = self._observe_route

    # -- feeds -------------------------------------------------------------

    def _observe_route(self, name: str, req: Request) -> None:
        with self._lock:
            self._recent.append(tuple(req.prompt))

    # -- state for health()/state_dump() -----------------------------------

    def _record_locked(self, name: str) -> _ReplicaRecord:
        rec = self._records.get(name)
        if rec is None:
            rec = self._records[name] = _ReplicaRecord()
        return rec

    def state(self) -> dict[str, dict]:
        """Per-replica restart state: ``restarts`` consumed,
        ``max_restarts``, ``permanent_dead``, next-attempt delay, and
        the attempt ``history`` (newest last)."""
        with self._lock:
            now = self.clock()
            return {name: {
                "restarts": rec.restarts,
                "max_restarts": self.max_restarts,
                "permanent_dead": rec.permanent_dead,
                "next_attempt_in_s": max(rec.next_ts - now, 0.0),
                "history": list(rec.history),
            } for name, rec in self._records.items()}

    def degraded(self) -> bool:
        """True while any replica is running on its restart budget —
        the fleet serves, but not at full redundancy headroom."""
        with self._lock:
            return any(rec.restarts > 0 or rec.permanent_dead
                       for rec in self._records.values())

    # -- the respawn loop --------------------------------------------------

    def tick(self) -> int:
        """One supervision pass (the router's poller calls this every
        poll): attempt a respawn for every dead, budgeted, backed-off
        replica.  Returns how many replicas rejoined."""
        with self.router._lock:
            # A cordoned replica is being drained out of the fleet by
            # the autoscaler: if it dies mid-drain its in-flight work
            # fails over, but respawning it would fight the retire.
            dead = [r for r in self.router.replicas
                    if r.name in self.router._dead
                    and r.name not in self.router._cordoned]
        rejoined = 0
        for handle in dead:
            if self._respawn(handle):
                rejoined += 1
        return rejoined

    def _factory_for(self, handle: ReplicaHandle) -> \
            "Callable[[], ReplicaHandle | None] | None":
        fac = self.factories.get(handle.name)
        if fac is not None:
            return fac
        if isinstance(handle, LocalReplica):
            return lambda: self._default_local_factory(handle)
        return None     # HTTP replicas heal through probes

    def _respawn(self, handle: ReplicaHandle) -> bool:
        name = handle.name
        factory = self._factory_for(handle)
        if factory is None:
            return False
        now = self.clock()
        with self._lock:
            rec = self._record_locked(name)
            if rec.permanent_dead or now < rec.next_ts:
                return False
            if rec.restarts >= self.max_restarts:
                rec.permanent_dead = True
                self.metrics.counter(
                    "supervisor.permanent_deaths").inc()
                self.metrics.event("supervisor.permanent_death",
                                   replica=name,
                                   restarts=rec.restarts)
                return False
            # Burn the budget up front: a factory that crashes (or a
            # firing serve.supervisor fault) must still advance the
            # backoff, or a broken factory hot-loops every tick.
            rec.restarts += 1
            rec.next_ts = now + self.backoff_s * (2 ** (rec.restarts - 1))
        try:
            self.faults.check("serve.supervisor", key=name)
            replacement = factory()
        except Exception as e:
            self.metrics.counter("supervisor.respawn_failures").inc()
            self.metrics.event("supervisor.respawn_failure",
                               replica=name, error=str(e))
            with self._lock:
                self._records[name].history.append(
                    {"ok": False, "error": str(e)})
            return False
        with self._lock:
            rec = self._records[name]
            rec.history.append({"ok": True, "error": None})
            restarts = rec.restarts
        self.metrics.counter("supervisor.respawns").inc()
        self.metrics.event("supervisor.respawn", replica=name,
                           restarts=restarts,
                           out_of_band=replacement is None)
        if replacement is None:
            return False    # out-of-band: probes will revive the handle
        self.router.replace_replica(name, replacement)
        return True

    # -- elastic membership (the autoscaler's factory seam) ----------------

    def spawn_replica(self, name: str,
                      template: "ReplicaHandle | None" = None,
                      ) -> "ReplicaHandle | None":
        """Build a brand-new replica handle for the autoscaler's grow
        path, through the same pluggable factory seam respawn uses: an
        explicit ``factories[name]`` entry wins; otherwise a live
        local replica (``template``, or the first healthy
        :class:`~horovod_tpu.router.LocalReplica`) is cloned via
        :func:`clone_engine` and pre-warmed with its hot prompts.
        Returns ``None`` when no factory applies (an all-HTTP fleet
        grows out-of-band)."""
        fac = self.factories.get(name)
        if fac is not None:
            return fac()
        if template is None:
            with self.router._lock:
                live = [r for r in self.router.replicas
                        if r.name not in self.router._dead
                        and isinstance(r, LocalReplica)]
            template = live[0] if live else None
        if not isinstance(template, LocalReplica):
            return None
        eng = clone_engine(template.engine)
        # Warm from the template's shadow: the newcomer inherits the
        # fleet's hot prefixes instead of joining with a cold radix.
        self._warm(eng, template.name)
        return LocalReplica(eng, name=name, faults=template.faults)

    def forget(self, name: str) -> None:
        """Drop a retired replica's restart record so a future replica
        reusing the name starts with a full budget (the autoscaler
        calls this after :meth:`~horovod_tpu.router.RouterServer.retire_replica`)."""  # noqa: E501
        with self._lock:
            self._records.pop(name, None)

    # -- warm respawn ------------------------------------------------------

    def _default_local_factory(self,
                               dead: LocalReplica) -> ReplicaHandle:
        eng = clone_engine(dead.engine)
        self._warm(eng, dead.name)
        return LocalReplica(eng, name=dead.name, faults=dead.faults)

    def _warm_candidates(self, name: str) -> "list[tuple[int, ...]]":
        """Recently routed prompts the dead replica's shadow index
        recognises, newest first, deduped, bounded by
        ``warm_prefixes``."""
        if self.warm_prefixes <= 0:
            return []
        with self.router._lock:
            shadow = self.router._shadows.get(name)
        with self._lock:
            recent = list(self._recent)
        out: list[tuple[int, ...]] = []
        seen: set[tuple[int, ...]] = set()
        for prompt in reversed(recent):
            if prompt in seen:
                continue
            seen.add(prompt)
            if shadow is not None and shadow.match_tokens(prompt) > 0:
                out.append(prompt)
                if len(out) >= self.warm_prefixes:
                    break
        return out

    def _warm(self, eng: Any, name: str) -> None:
        """Best-effort prefix-cache rewarm: run each hot prompt for one
        token so its chunks land in the fresh radix.  Failures are
        swallowed — warm-up is an optimization, never a respawn
        blocker."""
        if getattr(eng, "prefix", None) is None:
            return
        for prompt in self._warm_candidates(name):
            try:
                eng.run([Request(prompt=list(prompt), max_new_tokens=1)])
                self.metrics.counter("supervisor.warm_prefixes").inc()
            except Exception:
                continue    # one bad prompt must not cold-start the rest


def supervise(router: RouterServer,
              **kwargs: Any) -> ReplicaSupervisor:
    """Attach a :class:`ReplicaSupervisor` to ``router`` (convenience
    constructor mirroring ``maybe_start_router``'s shape)."""
    return ReplicaSupervisor(router, **kwargs)
