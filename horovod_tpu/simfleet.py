"""Fleet-scale simulation harness: hundreds of replicas under chaos
through the REAL control plane.

Every robustness mechanism in the serving stack — failover replay, the
crash-durable journal, supervisor respawn, cordon→drain→retire,
burn-rate alerts, the autoscaler's guards — runs unmodified here; only
the replicas are simulated.  A :class:`SimReplica` models prefill /
decode / queue latency from a measured :class:`PhaseProfile` (seeded
per-replica jitter, finite KV capacity, straggler and slow-start
modes) instead of running jax, and a :class:`SimFleet` driver advances
the router poll pass, sampler ticks, supervisor backoff clocks, and
alert hysteresis windows on one shared :class:`SimClock` — so a
campaign of 200+ replicas × 100k+ requests, with crash storms,
partition waves, straggler epidemics, and KV-exhaustion ramps, runs in
seconds of wall time and is bit-reproducible from its seed.

The split mirrors :mod:`horovod_tpu.loadgen`'s ``VirtualClock`` (time
is synthetic, order is real): everything the control plane *computes*
— ticket stamps, reap TTLs, backoff deadlines, alert windows — reads
the injected clock, while the poll pass itself still costs real host
work (``router.poll_s`` measures that on the wall; the sub-linear
oracle keys off it).

Campaign oracles (:func:`run_sim_campaign`) extend the chaos set:
keyed requests stay exactly-once across crash storms and epoch bumps,
tickets and journal memory stay bounded, every fired alert resolves,
the autoscaler converges without flapping, the shadow-index union
respects the fleet byte ceiling, and the poll pass stays sub-linear
per replica as the fleet grows.  Reports share the
:func:`horovod_tpu.chaos.compare_campaigns` gate shape, so
``tools/simfleet_run.py --compare`` reuses it verbatim.
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import math
import random
import time
from typing import Any, Callable, Sequence

from horovod_tpu import metrics as metrics_mod
from horovod_tpu.loadgen import Poisson, RequestMix, TenantSpec, \
    build_schedule
from horovod_tpu.monitor import env_float
from horovod_tpu.router import ReplicaHandle, RouterServer
from horovod_tpu.serving import FAILED, OK, REJECTED, Request, \
    RequestResult
from horovod_tpu.supervisor import ReplicaSupervisor


class SimClock:
    """The shared virtual clock: a zero-arg callable (the shape every
    control-plane ``clock=`` seam takes) whose time only moves when the
    driver says so.  The whole fleet — router bookkeeping, supervisor
    backoff, sampler cadence, alert hysteresis — reads one instance, so
    a campaign's notion of "now" is a pure function of the step loop."""

    def __init__(self, start: float = 0.0) -> None:
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


@dataclasses.dataclass(frozen=True)
class PhaseProfile:
    """Measured per-phase latency model (the serve profiler's report
    shape, collapsed to a linear fit): a request's service time is
    ``prefill_base_s + prefill_s_per_token * len(prompt) +
    decode_s_per_token * max_new_tokens``.  Defaults approximate the
    CPU rehearsal engine; campaigns can load real ``serve.phase.*``
    fits without touching the driver."""

    prefill_base_s: float = 0.012
    prefill_s_per_token: float = 0.0004
    decode_s_per_token: float = 0.009

    def service_s(self, prompt_tokens: int, new_tokens: int) -> float:
        return (self.prefill_base_s
                + self.prefill_s_per_token * prompt_tokens
                + self.decode_s_per_token * new_tokens)


def sim_tokens(req: Request) -> list[int]:
    """The simulated engine's deterministic output: a pure function of
    the request, so failover replay on a different SimReplica is
    bit-identical to the first attempt — the same greedy-determinism
    contract the real engine gives the router."""
    h = 0
    for tok in req.prompt:
        h = (h * 1000003 + int(tok) + 1) & 0xFFFFFFFF
    return [(h + i) % 50257 for i in range(req.max_new_tokens)]


class SimReplica(ReplicaHandle):
    """A latency-model replica behind the real handle interface.

    Single-threaded by contract: the driver owns submit / advance /
    probe (no pump thread, no locks), and completion callbacks fire
    inside :meth:`advance_to` — reentrantly safe against the router's
    failover path, which may submit back into another SimReplica from
    within a callback (the ``LocalReplica`` dead-on-arrival precedent).

    Chaos surface: :meth:`kill` (process loss — every in-flight and
    queued callback fires ``None``, the router's failover signal),
    :meth:`partition` (probes raise for a window; the replica keeps
    serving, modeling a healthy backend behind a broken health path),
    :meth:`set_slow` (straggler multiplier), and :meth:`leak_kv` /
    :meth:`heal_kv` (KV-exhaustion pressure: leaked blocks admit
    nothing until healed).  ``can_revive`` is True so a healed
    partition rejoins through probe revival, while a kill heals
    through the supervisor's factory respawn."""

    can_revive = True

    def __init__(self, name: str, clock: Callable[[], float], *,
                 profile: "PhaseProfile | None" = None, seed: int = 0,
                 n_slots: int = 4, kv_blocks: int = 64,
                 tokens_per_block: int = 16, jitter: float = 0.08,
                 slow_start_s: float = 0.0,
                 slow_start_factor: float = 3.0):
        self.name = name
        self.clock = clock
        self.profile = profile if profile is not None else PhaseProfile()
        self.block_size = tokens_per_block
        self.n_slots = n_slots
        self.kv_blocks = kv_blocks
        self.tokens_per_block = tokens_per_block
        self.jitter = jitter
        self.slow_start_s = slow_start_s
        self.slow_start_factor = slow_start_factor
        self.rng = random.Random(f"simreplica:{seed}:{name}")
        self.born_t = clock()
        self.slow_factor = 1.0
        self.dead = False
        self.completed = 0
        self.submitted = 0
        #: Fired with the request on every admission — the fleet's
        #: execution odometer (exactly-once accounting sees replays).
        self.on_execute: "Callable[[Request], None] | None" = None
        self._free = kv_blocks
        self._leaked = 0
        self._queue: collections.deque = collections.deque()
        self._running: list = []        # heap of (finish_t, seq, ...)
        self._seq = 0
        self._partition_until: "float | None" = None

    # -- handle interface --------------------------------------------------

    def submit(self, req: Request, done_cb: Callable) -> None:
        if self.dead:
            done_cb(None)       # dead on arrival: failover signal
            return
        self.submitted += 1
        if not req.prompt:
            # Poison request: the simulated engine load-sheds it the
            # way the real admission path does — terminal REJECTED,
            # no collateral damage.
            done_cb(RequestResult([], REJECTED))
            return
        self._queue.append((req, done_cb, self.clock()))
        self._admit(self.clock())

    def probe(self) -> dict:
        now = self.clock()
        if self._partition_until is not None:
            if now < self._partition_until:
                raise ConnectionError(
                    f"{self.name}: probe partitioned until "
                    f"{self._partition_until:g}")
            self._partition_until = None
        if self.dead:
            return {"healthy": False}
        return {
            "healthy": True,
            "inflight": len(self._running),
            "queue_depth": len(self._queue),
            "goodput": min(1.0, 1.0 / max(self._slow_mult(now), 1.0)),
            "free_kv_frac": max(self._free - self._leaked, 0)
            / max(self.kv_blocks, 1),
            "tp_size": 1,
        }

    def stop(self) -> None:
        # Retire/replace path: anything still on board fails over.
        self.kill()

    # -- the latency model -------------------------------------------------

    def _slow_mult(self, now: float) -> float:
        mult = self.slow_factor
        if self.slow_start_s > 0 and now - self.born_t < self.slow_start_s:
            mult *= self.slow_start_factor
        return mult

    def _blocks_for(self, req: Request) -> int:
        tokens = len(req.prompt) + req.max_new_tokens
        return max(math.ceil(tokens / max(self.tokens_per_block, 1)), 1)

    def _admit(self, now: float) -> None:
        while self._queue and len(self._running) < self.n_slots:
            req, cb, _t = self._queue[0]
            blocks = self._blocks_for(req)
            if blocks > self._free - self._leaked:
                break           # KV pressure: wait for frees (or heal)
            self._queue.popleft()
            self._free -= blocks
            service = (self.profile.service_s(len(req.prompt),
                                              req.max_new_tokens)
                       * self._slow_mult(now)
                       * self.rng.uniform(1.0 - self.jitter,
                                          1.0 + self.jitter))
            self._seq += 1
            heapq.heappush(self._running,
                           (now + service, self._seq, req, cb, blocks))
            if self.on_execute is not None:
                self.on_execute(req)

    def advance_to(self, now: float) -> int:
        """Fire every completion due by virtual ``now``, then admit
        from the queue; returns how many requests finished."""
        if self.dead:
            return 0
        fired = 0
        while self._running and self._running[0][0] <= now:
            _t, _seq, req, cb, blocks = heapq.heappop(self._running)
            self._free += blocks
            self.completed += 1
            fired += 1
            cb(RequestResult(sim_tokens(req), OK))
        if fired or self._queue:
            self._admit(now)
        return fired

    # -- chaos surface -----------------------------------------------------

    def kill(self) -> None:
        """Process loss: every accepted-but-unfinished request fires
        ``None`` so the router replays it on survivors.  Idempotent."""
        if self.dead:
            return
        self.dead = True
        pending = [cb for _t, _s, _r, cb, _b in self._running]
        pending.extend(cb for _r, cb, _t in self._queue)
        self._running = []
        self._queue.clear()
        self._free = self.kv_blocks
        self._leaked = 0
        for cb in pending:
            cb(None)

    def partition(self, duration_s: float) -> None:
        """Probes raise for ``duration_s`` of virtual time; serving
        continues underneath (the classic health-path partition)."""
        self._partition_until = self.clock() + duration_s

    def set_slow(self, factor: float) -> None:
        self.slow_factor = max(float(factor), 1.0)

    def leak_kv(self, frac: float) -> int:
        """Mark ``frac`` of this replica's TOTAL KV pool leaked —
        unavailable to admission until :meth:`heal_kv` — and return the
        leaked block count."""
        self._leaked = min(int(self.kv_blocks * frac), self.kv_blocks)
        return self._leaked

    def heal_kv(self) -> None:
        self._leaked = 0


class SimSupervisor(ReplicaSupervisor):
    """The supervisor with a whole-namespace factory seam: ANY dead
    replica respawns as (and any autoscaler grow spawns) a fresh
    :class:`SimReplica` from the owning fleet's template — the real
    respawn bookkeeping (budget, backoff, replace_replica) stays in
    charge; only handle construction is simulated."""

    def __init__(self, router: RouterServer, fleet: "SimFleet",
                 **kw: Any) -> None:
        super().__init__(router, **kw)
        self._fleet = fleet

    def _factory_for(self, handle: ReplicaHandle):
        return lambda: self._fleet.make_replica(handle.name)

    def spawn_replica(self, name: str,
                      template: "ReplicaHandle | None" = None,
                      ) -> "ReplicaHandle | None":
        return self._fleet.make_replica(name)


class SimFleet:
    """N simulated replicas behind one REAL router + supervisor +
    autoscaler + alert plane, all on a shared :class:`SimClock`.

    The driver is single-threaded: :meth:`run` interleaves chaos
    events, arrival submission, replica advancement, fleet-gauge
    refresh, and the router's ``poll_now`` pass per virtual step, then
    sweeps terminal tickets so the ticket table tracks true in-flight.
    Nothing sleeps; virtual seconds cost microseconds."""

    def __init__(self, n_replicas: int, *, seed: int = 0,
                 profile: "PhaseProfile | None" = None,
                 policy: str = "round_robin",
                 journal: "str | None" = None,
                 n_slots: int = 4, kv_blocks: int = 64,
                 tokens_per_block: int = 16, jitter: float = 0.08,
                 sample_s: float = 0.25,
                 alert_time_scale: float = 0.05,
                 poll_every: float = 0.2, probe_fails: int = 2,
                 shadow_max_bytes: "int | None" = None,
                 ticket_ttl_s: float = 600.0,
                 supervise_backoff_s: float = 0.25,
                 max_restarts: int = 4,
                 autoscale_cooldown_s: float = 2.0,
                 autoscale_drain_s: float = 5.0,
                 max_replicas: "int | None" = None,
                 knee_rps: "float | None" = None,
                 slo_window: int = 512):
        from horovod_tpu import alerts as alerts_mod
        from horovod_tpu import timeseries as timeseries_mod
        from horovod_tpu.autoscaler import FleetAutoscaler

        self.seed = seed
        self.profile = profile if profile is not None else PhaseProfile()
        self.n_slots = n_slots
        self.kv_blocks = kv_blocks
        self.tokens_per_block = tokens_per_block
        self.jitter = jitter
        self.poll_every = poll_every
        self.clock = SimClock()
        self.registry = metrics_mod.MetricsRegistry()
        self.executions: collections.Counter = collections.Counter()
        #: Every SimReplica ever constructed — replaced handles must be
        #: reaped (see ``_kill_orphans``) or their callbacks leak.
        self._spawned: list[SimReplica] = []
        replicas = [self.make_replica(f"sim{i}")
                    for i in range(n_replicas)]
        self.sampler = timeseries_mod.MetricsSampler(
            self.registry, sample_s=sample_s, raw_points=4096,
            clock=self.clock)
        self.alerts = alerts_mod.AlertManager(
            self.sampler, registry=self.registry,
            time_scale=alert_time_scale, clock=self.clock)
        self.router = RouterServer(
            replicas, policy=policy, registry=self.registry,
            sampler=self.sampler, alerts=self.alerts, journal=journal,
            poll_s=poll_every, probe_fails=probe_fails,
            ticket_ttl_s=ticket_ttl_s, drain_s=0.0,
            shadow_max_bytes=shadow_max_bytes, clock=self.clock)
        if knee_rps is not None:
            # Demand-sized advisor over the same virtual clock: the
            # knee a real bench would have written.
            self.router.advisor = alerts_mod.CapacityAdvisor(
                self.sampler, alerts=self.alerts,
                registry=self.registry,
                load_report={"serve_load_knee_goodput_rps": knee_rps},
                window_s=10.0, clock=self.clock)
        self.supervisor = SimSupervisor(
            self.router, self, max_restarts=max_restarts,
            backoff_s=supervise_backoff_s, warm_prefixes=0,
            clock=self.clock)
        self.autoscaler = FleetAutoscaler(
            self.router, supervisor=self.supervisor, enabled=False,
            cooldown_s=autoscale_cooldown_s, stable_s=0.0,
            min_replicas=1,
            max_replicas=(max_replicas if max_replicas is not None
                          else n_replicas + 8),
            step=8, drain_s=autoscale_drain_s, clock=self.clock)
        # Windowed fleet SLO accounting behind the serve.* gauges the
        # advisor and burn-rate rules read.
        self._slo_window: collections.deque = collections.deque(
            maxlen=slo_window)
        self._completed_total = 0
        self._completed_gauged = 0
        self.outstanding: dict[int, dict] = {}
        self.stats = {"submitted": 0, "delivered": 0, "ok": 0,
                      "rejected": 0, "failed": 0, "mismatches": 0,
                      "steps": 0, "polls": 0}
        self.keyed_results: dict[str, tuple[str, tuple]] = {}

    # -- replica factory ---------------------------------------------------

    def make_replica(self, name: str) -> SimReplica:
        """Template factory for initial build, supervisor respawn, and
        autoscaler grow alike — a pure function of (fleet seed, name),
        so a respawned replica's jitter stream is reproducible."""
        r = SimReplica(name, self.clock, profile=self.profile,
                       seed=self.seed, n_slots=self.n_slots,
                       kv_blocks=self.kv_blocks,
                       tokens_per_block=self.tokens_per_block,
                       jitter=self.jitter)
        r.on_execute = self._on_execute
        self._spawned.append(r)
        return r

    def _on_execute(self, req: Request) -> None:
        self.executions[tuple(req.prompt)] += 1

    def sim_replicas(self) -> list[SimReplica]:
        return [r for r in list(self.router.replicas)
                if isinstance(r, SimReplica)]

    def _kill_orphans(self) -> None:
        """Kill any spawned handle the router no longer owns.  A real
        supervisor SIGKILLs the old process before committing a
        respawn, and the dying pump fires ``None`` for everything
        aboard; the sim equivalent is explicit — a replaced handle
        (e.g. a partitioned-but-alive replica the supervisor gave up
        on) must fail its passengers over or they hang forever."""
        current = {id(r): True for r in list(self.router.replicas)}
        survivors = []
        for r in self._spawned:
            if id(r) in current:
                survivors.append(r)
            elif not r.dead:
                r.kill()
        self._spawned = survivors

    # -- the step loop -----------------------------------------------------

    def submit(self, req: Request, *, arrival_t: float,
               key: "str | None" = None) -> int:
        rid = self.router.route(req, idempotency_key=key)
        self.stats["submitted"] += 1
        self.outstanding[rid] = {"t": arrival_t, "req": req, "key": key}
        return rid

    def _sweep(self, now: float) -> int:
        """Collect every terminal ticket (scoring SLO and bit-stability
        on the way) and reap it, so the ticket table only ever holds
        true in-flight work."""
        done = 0
        for rid in list(self.outstanding):
            res = self.router.result(rid, timeout=0)
            if res is None:
                continue
            rec = self.outstanding.pop(rid)
            done += 1
            self.stats["delivered"] += 1
            req = rec["req"]
            if res.status == OK:
                self.stats["ok"] += 1
                if list(res) != sim_tokens(req):
                    self.stats["mismatches"] += 1
                met = (req.slo_s is None
                       or now - rec["t"] <= req.slo_s)
                self._slo_window.append(1 if met else 0)
                self._completed_total += 1
            elif res.status == REJECTED:
                self.stats["rejected"] += 1
                self._slo_window.append(0)
            else:
                self.stats["failed"] += 1
                self._slo_window.append(0)
            if rec["key"] is not None:
                self.keyed_results[rec["key"]] = (res.status,
                                                  tuple(res))
        if done:
            self.router.reap_tickets(0.0)
        return done

    def _refresh_gauges(self) -> None:
        """Drive the fleet-level serve.* series the advisor and alert
        rules read — the aggregation the real fleet's engines feed."""
        reps = self.sim_replicas()
        queue = sum(len(r._queue) for r in reps)
        free = sum(max(r._free - r._leaked, 0) for r in reps)
        if self._slo_window:
            goodput = sum(self._slo_window) / len(self._slo_window)
        else:
            goodput = 1.0
        self.registry.gauge("serve.goodput").set(goodput)
        self.registry.gauge("serve.queue_depth").set(queue)
        self.registry.gauge("kv.free_blocks").set(free)
        delta = self._completed_total - self._completed_gauged
        if delta:
            self.registry.counter("serve.requests_completed").inc(delta)
            self._completed_gauged = self._completed_total

    def run(self, schedule: Sequence[Any], *,
            events: Sequence[tuple] = (), step_s: float = 0.05,
            key_every: int = 0, settle_s: float = 30.0,
            max_virtual_s: float = 600.0) -> dict:
        """Drive the whole offered ``schedule`` (loadgen ``Arrival``
        rows) plus chaos ``events`` (``(t, fn)`` pairs, ``fn(fleet)``)
        through the fleet, then settle: keep ticking until everything
        is terminal, no alert is firing, and no drain is in flight —
        so "every fired alert resolves" is observed, not assumed.
        ``key_every > 0`` gives every k-th arrival an idempotency key
        (requires a journaled router).  Returns the run stats."""
        arrivals = collections.deque(schedule)
        pending_events = collections.deque(
            sorted(events, key=lambda e: e[0]))
        traffic_end = schedule[-1].t if len(schedule) else 0.0
        next_poll = 0.0
        idx = 0
        wall0 = time.perf_counter()
        while True:
            now = self.clock()
            while pending_events and pending_events[0][0] <= now:
                _t, fn = pending_events.popleft()
                fn(self)
            while arrivals and arrivals[0].t <= now:
                a = arrivals.popleft()
                key = (f"sim-key-{idx}"
                       if key_every and idx % key_every == 0 else None)
                self.submit(a.req, arrival_t=a.t, key=key)
                idx += 1
            for r in self.sim_replicas():
                r.advance_to(now)
            self._refresh_gauges()
            if now >= next_poll:
                self.router.poll_now()
                self._kill_orphans()
                self.stats["polls"] += 1
                next_poll = now + self.poll_every
            self._sweep(now)
            self.stats["steps"] += 1
            if (not arrivals and not pending_events
                    and not self.outstanding
                    and now >= traffic_end + settle_s
                    and not self.alerts.firing()
                    and not self.autoscaler.draining()):
                break
            if now >= max_virtual_s:
                break       # stall backstop: oracles will tell
            self.clock.advance(step_s)
        out = dict(self.stats)
        out["virtual_s"] = self.clock()
        out["wall_s"] = time.perf_counter() - wall0
        return out

    def close(self) -> None:
        self.router.stop()


# -- chaos-at-scale scenario builders --------------------------------------


def crash_storm(seed: int, *, n_kills: int, t0: float,
                t1: float) -> list[tuple]:
    """Seeded kill schedule: ``n_kills`` process losses at uniform
    times in ``[t0, t1)``, each victim drawn at fire time from the
    then-alive simulated replicas (so a respawned replica is back in
    the blast radius — the production property)."""
    rng = random.Random(f"sim-crash:{seed}")
    times = sorted(rng.uniform(t0, t1) for _ in range(n_kills))

    def _kill(fleet: SimFleet) -> None:
        alive = [r for r in fleet.sim_replicas() if not r.dead]
        if alive:
            rng.choice(alive).kill()

    return [(t, _kill) for t in times]


def partition_wave(seed: int, *, t: float, frac: float,
                   duration_s: float) -> list[tuple]:
    """Correlated probe-failure injection: a contiguous ``frac`` of
    the fleet (a rack, a switch) answers no health probes for
    ``duration_s`` while still serving — the router must debounce,
    fail over routing, and revive them on heal."""
    rng = random.Random(f"sim-partition:{seed}")

    def _partition(fleet: SimFleet) -> None:
        reps = [r for r in fleet.sim_replicas() if not r.dead]
        if not reps:
            return
        n = max(int(len(reps) * frac), 1)
        start = rng.randrange(len(reps))
        for i in range(n):
            reps[(start + i) % len(reps)].partition(duration_s)

    return [(t, _partition)]


def straggler_epidemic(seed: int, *, t: float, frac: float,
                       factor: float, duration_s: float) -> list[tuple]:
    """A random subset of replicas slows by ``factor`` for
    ``duration_s`` — SLO misses accumulate, goodput sags, the
    burn-rate pair gets something to fire on — then recovers."""
    rng = random.Random(f"sim-straggler:{seed}")
    sick: list[SimReplica] = []

    def _infect(fleet: SimFleet) -> None:
        reps = [r for r in fleet.sim_replicas() if not r.dead]
        if not reps:
            return
        n = max(int(len(reps) * frac), 1)
        sick.extend(rng.sample(reps, min(n, len(reps))))
        for r in sick:
            r.set_slow(factor)

    def _recover(fleet: SimFleet) -> None:
        for r in sick:
            r.set_slow(1.0)

    return [(t, _infect), (t + duration_s, _recover)]


def kv_exhaustion(seed: int, *, t: float, frac: float,
                  duration_s: float, ramp_steps: int = 5,
                  leak_to: float = 0.95) -> list[tuple]:
    """A gradual KV leak across ``frac`` of the fleet: free blocks
    ramp down over ``ramp_steps`` events (a believable slope for the
    ``kv_exhaustion`` time-to-empty alert), pin near exhaustion, then
    heal at ``t + duration_s``."""
    rng = random.Random(f"sim-kv:{seed}")
    leaking: list[SimReplica] = []

    def _start(fleet: SimFleet) -> None:
        reps = [r for r in fleet.sim_replicas() if not r.dead]
        if not reps:
            return
        n = max(int(len(reps) * frac), 1)
        leaking.extend(rng.sample(reps, min(n, len(reps))))

    def _leak(step: int) -> Callable:
        def _fn(fleet: SimFleet) -> None:
            for r in leaking:
                if not r.dead:
                    r.leak_kv(leak_to * (step + 1) / ramp_steps)
        return _fn

    def _heal(fleet: SimFleet) -> None:
        for r in leaking:
            r.heal_kv()

    ramp_span = duration_s * 0.6
    events: list[tuple] = [(t, _start)]
    events.extend((t + ramp_span * (i + 1) / ramp_steps, _leak(i))
                  for i in range(ramp_steps))
    events.append((t + duration_s, _heal))
    return events


def scripted_scale(t: float, action: str, n: int) -> list[tuple]:
    """A scripted autoscaler actuation (epoch bump under load): grow
    spawns fresh SimReplicas through the supervisor seam, shrink
    cordons a victim into the real drain→retire path."""

    def _actuate(fleet: SimFleet) -> None:
        fleet.autoscaler.actuate(
            {"action": action, "n": n,
             "reason": f"sim campaign scripted {action}"})

    return [(t, _actuate)]


# -- the campaign ----------------------------------------------------------

#: The campaign's two-tenant offered mix: the loadgen default shape
#: minus deadlines (virtual time would expire wall deadlines wrongly).
SIM_TENANTS: tuple = (
    TenantSpec("interactive", weight=3.0, prompt_len=(4, 12),
               new_tokens=(4, 8), shared_prefixes=4, prefix_len=16,
               slo_s=2.0),
    TenantSpec("batch", weight=1.0, prompt_len=(16, 40),
               new_tokens=(8, 16), slo_s=10.0),
)


def measure_poll_scaling(*, seed: int = 0, n_small: int = 50,
                         n_big: int = 200, polls: int = 20) -> dict:
    """Median wall cost of one idle ``poll_now`` pass at two fleet
    sizes.  The oracle wants per-replica cost roughly flat (an O(N²)
    regression shows up as the ratio approaching N_big/N_small); the
    pass is timed on the wall because the poll's host work is exactly
    what virtual time cannot compress."""
    costs = {}
    for n in (n_small, n_big):
        fleet = SimFleet(n, seed=seed)
        try:
            samples = []
            for _ in range(polls):
                t0 = time.perf_counter()
                fleet.router.poll_now()
                samples.append(time.perf_counter() - t0)
                fleet.clock.advance(fleet.poll_every)
            samples.sort()
            costs[n] = samples[len(samples) // 2]
        finally:
            fleet.close()
    per_small = costs[n_small] / n_small
    per_big = costs[n_big] / n_big
    ratio = per_big / per_small if per_small > 0 else float("inf")
    return {"n_small": n_small, "n_big": n_big,
            "poll_s_small": costs[n_small], "poll_s_big": costs[n_big],
            "per_replica_ratio": ratio,
            "sublinear": ratio <= 2.5}


def run_sim_campaign(*, seed: "int | None" = None,
                     n_replicas: "int | None" = None,
                     n_requests: "int | None" = None,
                     journal: "str | None" = None,
                     key_every: int = 100,
                     utilization: float = 0.45,
                     shadow_max_bytes: int = 256 * 1024,
                     poll_scaling: bool = True,
                     step_s: float = 0.05) -> dict:
    """One full fleet-scale chaos campaign through the real control
    plane, bit-reproducible from ``seed``: a Poisson workload sized to
    ``utilization`` of fleet capacity, overlaid with a crash storm,
    a partition wave, a straggler epidemic, a KV-exhaustion ramp, and
    two scripted autoscaler epoch bumps — then the invariant oracles.

    Defaults come from the env knobs (``HVD_TPU_SIM_SEED`` /
    ``HVD_TPU_SIM_REPLICAS`` / ``HVD_TPU_SIM_REQUESTS``); the report
    shares :func:`horovod_tpu.chaos.compare_campaigns`'s gate shape
    (``oracles`` / ``ok`` / ``ok_fraction``)."""
    import tempfile

    if seed is None:
        seed = int(env_float("HVD_TPU_SIM_SEED", 0))
    if n_replicas is None:
        n_replicas = int(env_float("HVD_TPU_SIM_REPLICAS", 200))
    if n_requests is None:
        n_requests = int(env_float("HVD_TPU_SIM_REQUESTS", 100000))
    if journal is None:
        journal = tempfile.mktemp(prefix=f"hvd-simfleet-{seed}-",
                                  suffix=".jsonl")

    profile = PhaseProfile()
    mean_service = profile.service_s(25, 8)
    capacity_rps = 4 * n_replicas / mean_service
    offered_rps = capacity_rps * utilization
    duration_s = 1.04 * n_requests / offered_rps

    # The two scripted epoch bumps sit 0.45*duration apart; the
    # cooldown guard must scale with the (request-count-dependent)
    # campaign duration or a short run silently holds the scale_down.
    fleet = SimFleet(n_replicas, seed=seed, profile=profile,
                     journal=journal,
                     shadow_max_bytes=shadow_max_bytes,
                     autoscale_cooldown_s=min(2.0, 0.1 * duration_s))
    mix = RequestMix(SIM_TENANTS, seed=seed)
    schedule = build_schedule(Poisson(offered_rps, seed), mix,
                              duration_s, seed)

    d = duration_s
    events: list[tuple] = []
    events += crash_storm(seed, n_kills=max(n_replicas // 10, 4),
                          t0=0.10 * d, t1=0.70 * d)
    events += partition_wave(seed, t=0.30 * d, frac=0.10,
                             duration_s=0.08 * d)
    events += straggler_epidemic(seed, t=0.45 * d, frac=0.15,
                                 factor=8.0, duration_s=0.15 * d)
    events += kv_exhaustion(seed, t=0.55 * d, frac=0.60,
                            duration_s=0.20 * d)
    events += scripted_scale(0.35 * d, "scale_up", 4)
    events += scripted_scale(0.80 * d, "scale_down", 2)

    try:
        stats = fleet.run(schedule, events=events, step_s=step_s,
                          key_every=key_every,
                          settle_s=max(0.8 * d, 20.0),
                          max_virtual_s=4.0 * d + 120.0)

        # Exactly-once probe: after every keyed original is terminal,
        # re-issue each key and demand the journaled answer — same
        # status, same bits, zero replica executions.
        router = fleet.router
        dedups_before = router.metrics.counter(
            "router.journal_dedups").value
        dup_mismatches = 0
        keyed = sorted(fleet.keyed_results.items())
        for key, (status, tokens) in keyed:
            rid = router.route(
                Request(prompt=list(range(3)), max_new_tokens=1),
                idempotency_key=key)
            dup = router.result(rid, timeout=0)
            if (dup is None or dup.status != status
                    or tuple(dup) != tokens):
                dup_mismatches += 1
        router.reap_tickets(0.0)
        dedups = (router.metrics.counter("router.journal_dedups").value
                  - dedups_before)

        leaked_tickets = router.memory_report()["tickets"]
        journal_results = len(router._journal_results)
        journal_inflight = len(router._journal_inflight)
        shadow_bytes = router._shadow_bytes()
        evictions = router.metrics.counter(
            "router.shadow_evictions").value
        _code, health = router.health()
        alert_states = fleet.alerts.states()
        fired_rules = sorted(n for n, st in alert_states.items()
                             if st["fired"])
        unresolved = sorted(n for n, st in alert_states.items()
                            if st["fired"] and st["state"] != "ok")
        asc_report = fleet.autoscaler.report()
        actions = [h for h in asc_report["history"]
                   if h.get("action") in ("scale_up", "scale_down")]
        flaps = [(a, b) for a, b in zip(actions, actions[1:])
                 if a["action"] != b["action"]
                 and b["t"] - a["t"] < fleet.autoscaler.cooldown_s]

        scaling = (measure_poll_scaling(seed=seed)
                   if poll_scaling else None)

        oracles = {
            "all_terminal": (stats["delivered"] == stats["submitted"]
                             and not fleet.outstanding),
            "bit_stable": stats["mismatches"] == 0
            and dup_mismatches == 0,
            "exactly_once": (dup_mismatches == 0
                             and dedups >= len(keyed)),
            "no_leaked_tickets": leaked_tickets == 0,
            "journal_bounded": (journal_results <= router.journal_keys
                                and journal_inflight == 0),
            "alerts_resolve": not unresolved,
            "alerts_exercised": len(fired_rules) > 0,
            "no_autoscaler_flap": (not flaps
                                   and not fleet.autoscaler.draining()),
            "epoch_advanced": asc_report["epoch"]["generation"] >= 2,
            "healed": health["healthy"] == health["replicas"],
            "shadow_bounded": (shadow_max_bytes <= 0
                               or shadow_bytes <= shadow_max_bytes),
        }
        if scaling is not None:
            oracles["poll_sublinear"] = scaling["sublinear"]
        report = {
            "seed": seed,
            "n_replicas": n_replicas,
            "n_requests": stats["submitted"],
            "n_ok": stats["ok"],
            "ok_fraction": (stats["ok"] / stats["submitted"]
                            if stats["submitted"] else 0.0),
            "delivered": stats["delivered"],
            "rejected": stats["rejected"],
            "failed": stats["failed"],
            "virtual_s": stats["virtual_s"],
            "wall_s": stats["wall_s"],
            "steps": stats["steps"],
            "polls": stats["polls"],
            "keyed": len(keyed),
            "journal_dedups": dedups,
            "failovers": int(router.metrics.counter(
                "router.failovers").value),
            "replica_deaths": int(router.metrics.counter(
                "router.replica_deaths").value),
            "respawns": int(router.metrics.counter(
                "supervisor.respawns").value),
            "shadow_bytes": shadow_bytes,
            "shadow_evictions": int(evictions),
            "alerts": {"fired": fired_rules, "unresolved": unresolved},
            "epoch": asc_report["epoch"]["generation"],
            "poll_scaling": scaling,
            "oracles": oracles,
            "ok": all(oracles.values()),
        }
        return report
    finally:
        fleet.close()


def measure_simfleet(*, seed: "int | None" = None,
                     n_replicas: "int | None" = None,
                     n_requests: "int | None" = None) -> dict:
    """The ``serve_simfleet_*`` bench arm: one seeded campaign at the
    configured scale, reporting throughput-in-virtual-time, goodput
    retention, and the oracle verdict (the gate key)."""
    report = run_sim_campaign(seed=seed, n_replicas=n_replicas,
                              n_requests=n_requests)
    return {
        "serve_simfleet_seed": report["seed"],
        "serve_simfleet_replicas": report["n_replicas"],
        "serve_simfleet_requests": report["n_requests"],
        "serve_simfleet_virtual_s": report["virtual_s"],
        "serve_simfleet_wall_s": report["wall_s"],
        "serve_simfleet_virtual_rps": (
            report["n_requests"] / report["virtual_s"]
            if report["virtual_s"] else 0.0),
        "serve_simfleet_ok_fraction": report["ok_fraction"],
        "serve_simfleet_failovers": report["failovers"],
        "serve_simfleet_respawns": report["respawns"],
        "serve_simfleet_oracles_ok": report["ok"],
    }
