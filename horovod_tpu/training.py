"""``fit`` — the Keras-frontend training loop.

Parity with the reference's Keras integration core
(reference: horovod/_keras/__init__.py:20-109 ``create_distributed_optimizer``
+ the callback protocol of horovod/_keras/callbacks.py): one call wires up
broadcast-at-start, per-batch distributed stepping, per-epoch metric
averaging, and the LR callbacks.  The distributed optimizer here is the
compiled :func:`horovod_tpu.DistributedOptimizer` (gradients all-reduced
inside the jitted step), so the loop body is one XLA program per batch.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax

from horovod_tpu import basics
from horovod_tpu.callbacks import Callback
from horovod_tpu.utils.compat import shard_map as _shard_map
from horovod_tpu.optim.distributed_optimizer import make_train_step


def make_eval_step(
    metric_fn: Callable[[Any, Any], dict],
    *,
    mesh=None,
    axis_name: str = basics.AXIS_NAME,
) -> Callable[[Any, Any], dict]:
    """Compile a distributed evaluation step.

    ``metric_fn(params, batch) -> {name: scalar}`` computes per-shard
    metrics; the returned function takes replicated ``params`` and a
    rank-major ``batch`` and returns the metrics averaged over the mesh —
    the compiled per-batch analogue of ``MetricAverageCallback``
    (reference horovod/_keras/callbacks.py:33-67 allreduces epoch metrics).
    """
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import collective_ops
    from horovod_tpu.ops.collective_ops import Average

    if mesh is None:
        mesh = basics.mesh()

    def step(params, batch):
        metrics = metric_fn(params, batch)
        return {
            k: collective_ops.allreduce(
                jnp.asarray(v), op=Average, axis_name=axis_name
            )
            for k, v in metrics.items()
        }

    jitted = jax.jit(
        _shard_map(
            step, mesh=mesh, in_specs=(P(), P(axis_name)), out_specs=P(),
            check_vma=False,
        )
    )
    if jax.default_backend() != "cpu":
        return jitted

    def throttled(params, batch):
        # CPU-simulation: cap in-flight collective launches at 1 (see
        # make_train_step's comment on the in-process rendezvous limit).
        out = jitted(params, batch)
        jax.block_until_ready(out)
        return out

    return throttled


def fit(
    params: Any,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable[[Any, Any], jax.Array],
    train_loader,
    *,
    epochs: int = 1,
    initial_epoch: int = 0,
    opt_state: Any = None,
    callbacks: Sequence[Callback] = (),
    eval_loader=None,
    eval_metric_fn: Callable[[Any, Any], dict] | None = None,
    verbose: bool = True,
) -> tuple[Any, Any, list[dict]]:
    """Train ``params`` with a compiled distributed step; returns
    ``(params, opt_state, history)``.

    * ``optimizer``: typically ``hvd.DistributedOptimizer(optax...)``.
    * ``train_loader``: yields rank-major batches (see
      :class:`horovod_tpu.data.ShardedLoader`); ``set_epoch`` is called per
      epoch when available (the DistributedSampler convention, reference
      examples/pytorch_mnist.py:50).
    * ``callbacks``: state pytree is ``(params, opt_state)`` — e.g.
      ``BroadcastGlobalVariablesCallback`` syncs both, matching the
      reference's broadcast of variables AND optimizer slots.
    * ``eval_metric_fn(params, batch) -> dict`` metrics are averaged over
      eval batches and merged into the epoch history.
    * ``initial_epoch``: first epoch index to run (the Keras resume
      parameter — reference examples/keras_imagenet_resnet50.py:171 passes
      ``initial_epoch=resume_from_epoch`` after the rank-0 checkpoint
      scan + broadcast); epoch-indexed callbacks (warmup/staircase
      schedules) then see the true epoch number.
    """
    if opt_state is None:
        opt_state = optimizer.init(params)
    step = make_train_step(loss_fn, optimizer)

    state = (params, opt_state)
    for cb in callbacks:
        state = cb.on_train_begin(state)
    params, opt_state = state

    history: list[dict] = []
    for epoch in range(initial_epoch, epochs):
        if hasattr(train_loader, "set_epoch"):
            train_loader.set_epoch(epoch)
        state = (params, opt_state)
        for cb in callbacks:
            state = cb.on_epoch_begin(epoch, state)
        params, opt_state = state

        losses = []
        for i, batch in enumerate(train_loader):
            state = (params, opt_state)
            for cb in callbacks:
                state = cb.on_batch_begin(i, state)
            params, opt_state = state
            out = step(params, opt_state, batch)
            params, opt_state = out.params, out.opt_state
            losses.append(out.loss)

        metrics = {"loss": float(jnp.mean(jnp.stack(losses)))} if losses else {}
        if eval_loader is not None and eval_metric_fn is not None:
            on_cpu = jax.default_backend() == "cpu"
            accum: dict[str, list] = {}
            for batch in eval_loader:
                m = eval_metric_fn(params, batch)
                if on_cpu:
                    # Same CPU-simulation throttle as make_train_step: cap
                    # in-flight collective launches at 1 (see the comment
                    # there on the in-process rendezvous limit).
                    jax.block_until_ready(m)
                for k, v in m.items():
                    accum.setdefault(k, []).append(v)
            for k, vs in accum.items():
                metrics[f"val_{k}"] = float(jnp.mean(jnp.stack(vs)))
        for cb in callbacks:
            metrics = cb.on_epoch_end(epoch, (params, opt_state), metrics)
        metrics = {
            k: float(v) if hasattr(v, "item") else v for k, v in metrics.items()
        }
        history.append(metrics)
        if verbose and basics.rank() == 0:
            line = "  ".join(f"{k}={v:.4f}" for k, v in metrics.items())
            print(f"Epoch {epoch + 1}/{epochs}  {line}")
    return params, opt_state, history
