"""Elastic / fault-tolerant training — the ``hvd.elastic`` API Horovod
grew in 0.20, re-shaped for TPU gangs.  BEYOND the 0.15.1 reference,
which has only stall *detection* (operations.cc:1424-1470) and clean
shutdown propagation (:1699-1729); see SURVEY §2.3's "Elastic" row.

TPU-native shape
----------------
GPU-era elastic keeps surviving processes alive and renegotiates a
smaller ring.  A TPU slice does not work that way: losing a worker means
losing its chips, and the platform reschedules the WHOLE slice — so gang
supervision belongs to the launcher (``horovod_tpu.launch --restarts N``
tears down and relaunches the entire gang on any worker death), and
elastic state must survive *process* death, not just collective failure.
Hence :class:`State` commits through the rank-0 orbax checkpoint pipeline
(:mod:`horovod_tpu.checkpoint`, async writes), and every (re)start of a
:func:`run`-wrapped function resumes from the newest commit.

In-process retry still exists for failures that do NOT kill the process
— a broken control plane, a shutdown response racing in-flight ops —
surfaced as :class:`~horovod_tpu.basics.HorovodInternalError`:
:func:`run` re-initializes the engine, restores the last commit, and
replays.  Deterministic caller mistakes (shape mismatches, bad
arguments) are plain ``ValueError``/``RuntimeError`` and propagate.

Usage (mirrors horovod.elastic; note the advance-THEN-commit shape —
progress counters are incremented before the commit so a restore never
replays work the commit already covers)::

    state = hvd.elastic.State(ckpt_dir="/ckpts/run1",
                              params=params, opt_state=opt_state,
                              epoch=0, batch=0)

    @hvd.elastic.run
    def train(state):
        while state.epoch < epochs:
            while state.batch < batches:
                state.params, state.opt_state, loss = step(
                    state.params, state.opt_state, data[state.batch])
                state.batch += 1
                if state.batch % 10 == 0:
                    state.commit()
            state.epoch += 1
            state.batch = 0
            state.commit()

    train(state)
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Any, Callable

import jax
import numpy as np

from horovod_tpu import basics, checkpoint
from horovod_tpu.basics import HorovodInternalError
from horovod_tpu.optim.distributed_optimizer import broadcast_optimizer_state

__all__ = ["BaseState", "State", "run", "HorovodInternalError"]

# Key under which State stores its own bookkeeping inside the committed
# tree (kept alongside user fields so one checkpoint is one commit).
_META = "__elastic__"


def _own(leaf: Any) -> Any:
    """A mutable, un-aliased copy of a numpy leaf.

    Durable restores hand back READ-ONLY numpy arrays, and adopting an
    array by reference would alias live state to the commit snapshot —
    a later in-place mutation of the field would silently corrupt the
    rollback point.  Every numpy leaf that crosses the snapshot/live
    boundary goes through here."""
    if isinstance(leaf, np.ndarray):
        return np.array(leaf)
    return leaf


class BaseState:
    """The interface :func:`run` keys on — any state object exposing
    commit / restore / sync (the JAX-native :class:`State` here, the
    torch frontend's :class:`horovod_tpu.torch_elastic.TorchState`, the
    keras frontend's :class:`horovod_tpu.keras_elastic.KerasState`)."""

    def commit(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


class LiveObjectState(BaseState):
    """Shared machinery for elastic state over LIVE framework objects
    (:class:`~horovod_tpu.torch_elastic.TorchState`,
    :class:`~horovod_tpu.keras_elastic.KerasState`): declared scalar
    fields with completeness guards, in-memory + durable rank-0 commits,
    and the restore order (durable walk → mem commit → plain sync).
    One copy of the protocol; subclasses supply the serializer and the
    object-slot specifics via the hooks below."""

    _reserved: tuple = ()       # object-slot attribute names
    _suffix: str = "bin"        # step_<N>.<suffix> commit files

    def _init_live(self, ckpt_dir, scalars: dict) -> None:
        for k in scalars:
            if k.startswith("_") or k in self._reserved:
                raise ValueError(f"reserved field name: {k!r}")
        object.__setattr__(self, "_scalars", dict(scalars))
        object.__setattr__(self, "_ckpt_dir",
                           os.path.abspath(ckpt_dir) if ckpt_dir else None)
        object.__setattr__(self, "_mem_commit", None)
        object.__setattr__(self, "_commit_step", 0)

    def __getattr__(self, name: str):
        scalars = object.__getattribute__(self, "_scalars")
        if name in scalars:
            return scalars[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value) -> None:
        if name in type(self)._reserved or name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        scalars = object.__getattribute__(self, "_scalars")
        if name in scalars:
            scalars[name] = value
        else:
            raise AttributeError(
                f"unknown state field {name!r}; declare every scalar in "
                f"{type(self).__name__}(...) so commits stay complete"
            )

    @property
    def commit_step(self) -> int:
        return object.__getattribute__(self, "_commit_step")

    def _adopt_scalars(self, incoming: dict) -> None:
        # Only DECLARED fields are adopted (same contract as State._adopt):
        # a commit from an older code revision must not inject undeclared
        # keys past the __setattr__ completeness guard.
        scalars = object.__getattribute__(self, "_scalars")
        for k in scalars:
            if k in incoming:
                scalars[k] = incoming[k]

    # -- subclass hooks ---------------------------------------------------

    def _snapshot(self) -> dict:
        raise NotImplementedError

    def _load_local(self, snap) -> None:
        raise NotImplementedError

    def _write_file(self, dst: str, snap) -> None:
        raise NotImplementedError

    def _read_file(self, path: str):
        raise NotImplementedError

    def _rank0(self) -> bool:
        raise NotImplementedError

    def _broadcast_obj(self, obj):
        raise NotImplementedError

    # -- the shared protocol ----------------------------------------------

    def commit(self) -> None:
        """Snapshot in host memory; rank 0 additionally writes
        ``step_N.<suffix>`` atomically (tmp + fsync + rename)."""
        object.__setattr__(self, "_commit_step", self.commit_step + 1)
        snap = self._snapshot()
        object.__setattr__(self, "_mem_commit", snap)
        ckpt_dir = object.__getattribute__(self, "_ckpt_dir")
        if ckpt_dir and self._rank0():
            os.makedirs(ckpt_dir, exist_ok=True)
            self._write_file(
                os.path.join(ckpt_dir,
                             f"step_{self.commit_step}.{self._suffix}"),
                snap)

    def restore(self) -> None:
        """Adopt the newest commit: durable ``step_N.<suffix>`` (root
        reads, everyone receives via sync) → in-memory snapshot → plain
        sync of the initial values."""
        ckpt_dir = object.__getattribute__(self, "_ckpt_dir")
        if ckpt_dir:
            outcome = restore_newest_commit(
                ckpt_dir, self._suffix, self._read_file, self._load_local,
                self._rank0(), self._broadcast_obj)
            if outcome == "ok":
                self.sync()         # root's loaded values fan out
                return
            if outcome is not None:
                raise RuntimeError(
                    f"elastic restore failed on root: {outcome}")
        mem = object.__getattribute__(self, "_mem_commit")
        if mem is not None:
            self._load_local(mem)
        self.sync()


def atomic_write(dst: str, write_fn: Callable[[Any], None]) -> None:
    """tmp + fsync + rename: a renamed commit file is a COMPLETE file.
    Without the fsync a power loss can persist the rename while payload
    blocks are still zeroed — a structurally-valid-but-corrupt file the
    restore walks' torn-write discrimination would then hard-fail on."""
    with open(dst + ".tmp", "wb") as f:
        write_fn(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(dst + ".tmp", dst)
    # The rename itself is only durable once the DIRECTORY entry is on
    # disk; without this a power loss can lose the (fully written,
    # fsynced) newest commit entirely and a resume silently replays work
    # the caller treated as committed.
    try:
        dfd = os.open(os.path.dirname(dst) or ".", os.O_RDONLY)
    except OSError:
        return     # platform without directory fds: best effort
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def restore_newest_commit(ckpt_dir: str, suffix: str,
                          read_file: Callable[[str], Any],
                          load_local: Callable[[Any], None],
                          is_root: bool,
                          broadcast_obj: Callable[[Any], Any]):
    """The shared durable-restore walk + outcome-agreement protocol
    (used by TorchState and KerasState; the serializer is the only
    per-frontend part).

    Newest-first scan of ``step_<N>.<suffix>``.  A file that fails
    ``zipfile.is_zipfile`` (both ``.pt`` and ``.npz`` are zips) is a
    torn mid-write kill: walk on to the previous commit LOUDLY (later
    commits renumber over the skipped step).  A structurally INTACT file
    whose payload fails to deserialize is not truncation — whatever the
    deserializer raised — so it hard-fails every rank instead of
    silently rolling back.  Every root-side failure becomes an outcome
    value agreed via ``broadcast_obj``; root always reaches that
    broadcast, so a root-only raise can never strand non-root ranks in
    the collective.  Returns the agreed outcome (None = no commit found,
    "ok" = loaded on root, else an error string)."""
    import re
    import zipfile

    outcome = None
    if is_root:
        try:
            snap = None
            if os.path.isdir(ckpt_dir):
                steps = sorted(
                    (int(m.group(1)) for m in (
                        re.fullmatch(rf"step_(\d+)\.{re.escape(suffix)}", e)
                        for e in os.listdir(ckpt_dir)) if m),
                    reverse=True)
                for s in steps:
                    path = os.path.join(ckpt_dir, f"step_{s}.{suffix}")
                    try:
                        snap = read_file(path)
                        break
                    except Exception as e:
                        if zipfile.is_zipfile(path):
                            raise
                        warnings.warn(
                            f"elastic restore: skipping unreadable "
                            f"checkpoint {path} ({type(e).__name__}: "
                            f"{e}); falling back to the previous commit",
                            stacklevel=2)
                        continue
            if snap is not None:
                load_local(snap)
                outcome = "ok"
        except Exception as e:
            outcome = f"{type(e).__name__}: {e}"
    return broadcast_obj(outcome)


class State(BaseState):
    """Named training state with commit / restore / sync semantics.

    ``fields`` are arbitrary pytrees (params, opt_state) or plain Python
    scalars (epoch, batch) — accessed as attributes.  ``commit()``
    snapshots them; ``restore()`` rolls back to the newest commit;
    ``sync()`` broadcasts the current values from the root process so a
    freshly (re)started gang agrees bit-for-bit.

    With ``ckpt_dir`` commits are durable (rank-0 async orbax writes — the
    reference's rank-0 checkpoint convention) and survive a launcher gang
    relaunch.  Without it commits live in host memory only: enough for
    in-process retry, gone with the process.
    """

    def __init__(self, ckpt_dir: str | None = None, *,
                 sync_commits: bool = False, **fields: Any) -> None:
        if not fields:
            raise ValueError("State needs at least one field, e.g. "
                             "State(params=params, epoch=0)")
        for k in fields:
            if k.startswith("_") or k == _META:
                raise ValueError(f"reserved field name: {k!r}")
        object.__setattr__(self, "_fields", dict(fields))
        object.__setattr__(self, "_ckpt_dir",
                           os.path.abspath(ckpt_dir) if ckpt_dir else None)
        # sync_commits=True makes commit() block until the write is on
        # disk: slower, but the commit is durable the moment it returns —
        # the right trade when the supervisor may SIGTERM the gang at any
        # moment (preemptible capacity).  (A reserved kwarg, not a field.)
        object.__setattr__(self, "_sync_commits", bool(sync_commits))
        object.__setattr__(self, "_mem_commit", None)
        object.__setattr__(self, "_commit_step", 0)

    # Attribute-style access to fields (state.params, state.epoch = 3).
    def __getattr__(self, name: str) -> Any:
        fields = object.__getattribute__(self, "_fields")
        if name in fields:
            return fields[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name in object.__getattribute__(self, "_fields"):
            object.__getattribute__(self, "_fields")[name] = value
        else:
            raise AttributeError(
                f"unknown state field {name!r}; declare every field in "
                f"State(...) so commits stay complete")

    @property
    def commit_step(self) -> int:
        """Monotonic count of commits (0 = never committed)."""
        return object.__getattribute__(self, "_commit_step")

    def _tree(self) -> dict:
        return {**object.__getattribute__(self, "_fields"),
                _META: {"commit_step": self.commit_step}}

    def commit(self) -> None:
        """Snapshot the current field values as the rollback/resume point.

        Host-memory snapshot always (``jax.device_get`` — a device-only
        snapshot would die with the engine on reinit); with ``ckpt_dir``
        also a durable rank-0 async checkpoint.  Async: the write costs a
        device→host copy up front, the disk I/O overlaps training
        (checkpoint.save_checkpoint); call sparingly — everything since
        the last commit is redone after a failure."""
        object.__setattr__(self, "_commit_step", self.commit_step + 1)
        live = self._tree()
        # device_get passes plain numpy leaves through unchanged (and
        # hands back memory-sharing views for ndarray subclasses like
        # np.memmap) — without the un-aliasing copy the snapshot would
        # share storage with the live field, and an in-place mutation
        # after commit() would corrupt the rollback point.
        snap = jax.tree.map(
            lambda l, s: np.array(s)
            if (isinstance(s, np.ndarray) and isinstance(l, np.ndarray)
                and np.shares_memory(s, l)) else s,
            live, jax.device_get(live))
        object.__setattr__(self, "_mem_commit", snap)
        ckpt_dir = object.__getattribute__(self, "_ckpt_dir")
        if ckpt_dir:
            checkpoint.save_checkpoint(
                ckpt_dir, snap, step=self.commit_step,
                async_save=not object.__getattribute__(self, "_sync_commits"))

    def sync(self) -> None:
        """Broadcast every field from the root process (reference resume
        recipe: load on rank 0 then broadcast_parameters,
        pytorch_imagenet_resnet50.py:134-142)."""
        # broadcast_optimizer_state (not broadcast_parameters): state
        # trees mix arrays with Python scalars (epoch/batch counters), and
        # it restores the scalar types after the wire trip.
        self._adopt(broadcast_optimizer_state(self._tree(), root_rank=0))

    def restore(self) -> None:
        """Adopt the newest commit, agreed across the gang.

        Priority: durable checkpoint (survives process death) → in-memory
        snapshot (in-process retry) → plain :meth:`sync` of the initial
        values (first-ever start).  Always ends with every rank holding
        identical values."""
        ckpt_dir = object.__getattribute__(self, "_ckpt_dir")
        if ckpt_dir:
            checkpoint.wait_for_checkpoints()   # a mid-flight async commit
            template = jax.device_get(self._tree())
            # Newest first, falling back past torn checkpoints (a gang
            # SIGTERMed mid-write leaves a partial step_N dir).  Every
            # rank raises or succeeds in agreement inside
            # restore_checkpoint, so the walk stays in lockstep.
            for cand in checkpoint.list_checkpoints(ckpt_dir):
                try:
                    self._adopt(checkpoint.restore_checkpoint(
                        cand, template=template))
                    return
                except HorovodInternalError:
                    # An environmental collective failure mid-restore is
                    # NOT a torn checkpoint: falling back here would
                    # silently resume from an older commit (and later
                    # commits would overwrite the newer good one).
                    # Propagate so run()'s retry reinits and re-attempts
                    # the NEWEST commit.
                    raise
                except RuntimeError:
                    continue
        mem = object.__getattribute__(self, "_mem_commit")
        if mem is not None:
            # The snapshot is process-local host memory; the broadcast
            # inside sync() re-establishes cross-rank agreement (ranks
            # may have diverged unevenly before the failure).
            self._adopt(mem)
        self.sync()

    def _adopt(self, tree: dict) -> None:
        meta = tree.get(_META, {})
        object.__setattr__(
            self, "_commit_step", int(meta.get("commit_step",
                                               self.commit_step)))
        fields = object.__getattribute__(self, "_fields")

        def _coerce(cur: Any, new: Any) -> Any:
            # Durable restores (orbax) come back as read-only numpy
            # arrays, including 0-d ones for fields declared as Python
            # scalars — `state.epoch += 1` would then die on "output
            # array is read-only".  Leaves declared as plain scalars are
            # cast back to their declared type (same restoration
            # broadcast_optimizer_state does after its wire trip);
            # numpy leaves come back as writable, un-aliased copies
            # (_own) so a field declared as a numpy buffer can be
            # mutated in place without corrupting the snapshot.
            if isinstance(cur, (bool, int, float)):
                return type(cur)(new)
            return _own(new)

        for k in fields:
            if k in tree:
                try:
                    fields[k] = jax.tree.map(_coerce, fields[k], tree[k])
                except (ValueError, TypeError):
                    # Structure drift (a field re-shaped between runs):
                    # adopt rather than refusing the commit — but say so
                    # (a silent adoption masks genuine commit/code
                    # mismatches), and still make the adopted leaves
                    # mutable: durable restores hand back READ-ONLY
                    # numpy arrays, the same failure _coerce prevents on
                    # the matched path.
                    warnings.warn(
                        f"elastic state field {k!r}: restored structure "
                        f"does not match the declared field; adopting the "
                        f"restored value as-is (check for model/optimizer "
                        f"code drift between commit and restore)",
                        stacklevel=2)
                    fields[k] = jax.tree.map(_own, tree[k])


def _reinit() -> None:
    """Tear the engine down (tolerating an already-dead one) and bring it
    back up for the retry — replaying the ORIGINAL ``init()`` arguments.

    A bare ``init()`` here would silently re-initialize a
    device-subset/custom-mesh world over ALL devices: ``hvd.size()``, the
    rank mapping, and data sharding would change mid-training with no
    error.  ``basics`` records the last init arguments (surviving
    ``shutdown()``) precisely so this replay reconstructs the same world.
    """
    import horovod_tpu as hvd

    devices, mesh_arg = basics._state.last_init_args or (None, None)
    try:
        hvd.shutdown()
    except Exception:
        pass
    hvd.init(devices=devices, mesh=mesh_arg)


def run(fn: Callable) -> Callable:
    """Decorator: make ``fn(state, ...)`` survive environmental collective
    failures (:class:`HorovodInternalError`) by reinit → restore → replay,
    up to ``HOROVOD_TPU_ELASTIC_RETRIES`` times (default 3).

    On entry the state is restored — so under a launcher gang relaunch
    (``horovod_tpu.launch --restarts``) the fresh process resumes from the
    newest durable commit with no extra code, and a first-ever start just
    syncs the initial values from root.  Mirrors ``horovod.elastic.run``
    (Horovod 0.20+)."""

    @functools.wraps(fn)
    def wrapper(state: BaseState, *args: Any, **kwargs: Any) -> Any:
        if not isinstance(state, BaseState):
            raise TypeError("first argument to an elastic.run function "
                            "must be an elastic.State (or TorchState / "
                            "KerasState)")
        basics._require_init()
        retries = int(os.environ.get("HOROVOD_TPU_ELASTIC_RETRIES", "3"))
        attempt = 0
        last_fail_commit: int | None = None
        need_restore = True
        while True:
            try:
                # restore() performs collectives (broadcast in sync /
                # restore_checkpoint) and can itself raise an
                # environmental HorovodInternalError — it lives INSIDE
                # the retried region so such a failure consumes an
                # attempt rather than aborting the elastic loop.
                if need_restore:
                    state.restore()
                    need_restore = False
                return fn(state, *args, **kwargs)
            except HorovodInternalError:
                # The budget bounds CONSECUTIVE unproductive failures, not
                # lifetime failures: durable progress since the previous
                # failure (commit_step advanced) resets it, so a long run
                # survives any number of well-separated transient blips
                # while a hard-down environment still exhausts quickly.
                commit = getattr(state, "commit_step", None)
                if (last_fail_commit is not None and commit is not None
                        and commit > last_fail_commit):
                    attempt = 0
                last_fail_commit = commit
                attempt += 1
                if attempt > retries:
                    raise
                _reinit()
                need_restore = True

    return wrapper
