"""Device-side observability for the serving engine: XLA cost model,
compile ledger, HBM accounting, transfer stamps, and live serving MFU.

Every observability plane so far stops at the host: the profiler tiles
the tick into phases but ``device_sync`` is one opaque mark, so nothing
says whether that wait was the device doing useful FLOPs or the host
stalled on a dispatch bubble.  :class:`DeviceTelemetry` opens that box
with four instruments, all derived from surfaces jax already exposes:

* **Static cost model** — at jit-pin time, :meth:`capture` runs
  ``jitfn.lower(*avals).compile().cost_analysis()`` per pinned program
  (``tick`` / ``chunk`` / ``set_row`` / ``spec_tick``), recording FLOPs
  and bytes-accessed *per dispatch*.  Ahead-of-time lowering never
  touches the jit call cache, so ``compile_cache_sizes()`` is identical
  telemetry-on vs off (pinned by tests/test_device_telemetry.py) and
  the retrace sentry stays silent.
* **Compile ledger** — each capture times its compile wall time
  (``device.compile_s`` histogram, ``device.compiles`` counter), and
  :meth:`on_retrace` charges the sentry's mid-serve cache growths with
  the captured per-program compile cost — retraces become seconds, not
  just a count.
* **HBM accounting** — :meth:`on_step` polls
  ``device.memory_stats()`` at the ``HVD_TPU_DEVICE_POLL_S`` cadence
  (``device.bytes_in_use`` / ``device.peak_bytes_in_use`` /
  ``device.hbm_used_fraction`` gauges where the backend provides them;
  CPU returns None and the gauges are simply never minted), reconciled
  in :meth:`report` against the engine's model-side byte accounting
  (params + paged KV pool) to expose framework overhead.
* **Transfer + dispatch split** — the engine stamps ``device_put`` /
  readback bytes per tick (``device.h2d_bytes`` / ``device.d2h_bytes``)
  and :meth:`on_sync` splits the measured ``device_sync`` wait into a
  cost-model-predicted device-compute share vs host stall, feeding the
  ``device_sync.compute_est`` / ``device_sync.host_stall`` nested
  profiler intervals and the ``device.overlap_headroom_pct`` gauge —
  the ceiling ROADMAP item 3's double-buffering work is judged against.

The live MFU (``serve.mfu``) divides achieved cost-model FLOPs/s by a
per-platform peak table (per chip, scaled by the engine's ``tp_size``);
on platforms the table doesn't know — every CPU rehearsal — the
``device.peak_flops_known`` gauge reads 0 and the MFU gauge is ABSENT,
never a dishonest zero.  ``HVD_TPU_PEAK_FLOPS`` overrides the per-chip
peak for hardware the table hasn't met.

Replay: one ``device.capture`` event per program plus one
``device.tick`` event per step land in the structured event log;
:func:`report_from_events` rebuilds the same report schema from those
records alone (no wall clock — a DETERMINISM_SURFACES row lets hvdlint
HVD010 police that), so ``tools/device_report.py`` renders and diffs a
crashed run identically to a live ``/device`` scrape.

Only :mod:`horovod_tpu.metrics` is imported at module level; jax loads
lazily inside the capture/poll paths so the replay-side consumers
(``tools/device_report.py``) stay import-light.
"""

from __future__ import annotations

import collections
import os
import time
import warnings
from typing import Any

from horovod_tpu import metrics as metrics_mod

#: The pinned jit programs the engine captures, in capture order
#: (``spec_tick`` only on spec engines).
PROGRAMS = ("tick", "chunk", "set_row", "spec_tick")

#: Dense per-chip peak FLOP/s by accelerator generation (bf16/fp32 as
#: served — published TPU peak matmul numbers), matched as lowercase
#: substrings of ``device_kind``.  Order matters: first match wins, so
#: longer/more specific keys come first.  CPUs (and any unmatched kind)
#: have NO honest peak — MFU is then not emitted at all.
PEAK_FLOPS_TABLE: tuple[tuple[str, float], ...] = (
    ("v6e", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

_DEFAULT_WINDOW = 256
_DEFAULT_POLL_S = 1.0


def _env_poll_s() -> float:
    raw = os.environ.get("HVD_TPU_DEVICE_POLL_S", "")
    try:
        return float(raw) if raw else _DEFAULT_POLL_S
    except ValueError:
        return _DEFAULT_POLL_S


def _env_peak_flops() -> float | None:
    """Per-chip peak override for hardware the table hasn't met."""
    raw = os.environ.get("HVD_TPU_PEAK_FLOPS", "")
    try:
        return float(raw) if raw else None
    except ValueError:
        warnings.warn(
            f"HVD_TPU_PEAK_FLOPS={raw!r} is not a float; ignoring",
            RuntimeWarning, stacklevel=2)
        return None


def lookup_peak_flops(device_kind: str) -> float | None:
    """Table lookup by device-kind substring; None = honest unknown."""
    kind = device_kind.lower()
    for key, peak in PEAK_FLOPS_TABLE:
        if key in kind:
            return peak
    return None


def normalize_cost_analysis(cost: Any) -> dict:
    """``Compiled.cost_analysis()`` returns a dict on new jax and a
    one-element list of dicts on older releases (None when the backend
    has no cost model); flatten to one plain dict."""
    if cost is None:
        return {}
    if isinstance(cost, (list, tuple)):
        out: dict = {}
        for entry in cost:
            if isinstance(entry, dict):
                out.update(entry)
        return out
    return dict(cost)


class DeviceTelemetry:
    """Per-engine device observability plane.

    The engine thread drives :meth:`dispatch` / :meth:`on_sync` /
    :meth:`on_step` once per program call / readback / step; the
    monitor thread calls :meth:`report` on scrape.  Only the rolling
    window ring crosses threads (same discipline as the profiler), and
    every hot-path call is gated at the engine by one ``is not None``
    test, so telemetry off costs nothing."""

    _GUARDED_BY_LOCK = ()  # ring mutations are engine-thread-only;
    # report() reads a consistent-enough copy (plain-dict snapshots).

    def __init__(self, registry: "metrics_mod.MetricsRegistry",
                 *, n_devices: int = 1, window: int | None = None,
                 poll_s: float | None = None,
                 peak_flops: float | None = None):
        self.metrics = registry
        self.n_devices = max(int(n_devices), 1)
        self.window = _DEFAULT_WINDOW if window is None else int(window)
        if self.window < 1:
            raise ValueError(
                f"device window must be >= 1, got {self.window}")
        self.poll_s = _env_poll_s() if poll_s is None else float(poll_s)
        self.platform, self.device_kind = self._identify()
        per_chip = (peak_flops if peak_flops is not None
                    else _env_peak_flops())
        self.peak_source = "arg" if peak_flops is not None else (
            "env" if per_chip is not None else "table")
        if per_chip is None:
            per_chip = lookup_peak_flops(self.device_kind)
        if per_chip is None:
            self.peak_source = None
        self.peak_flops = (per_chip * self.n_devices
                           if per_chip is not None else None)
        self.peak_flops_known = self.peak_flops is not None
        #: per-program cost-model rows: flops / bytes_accessed /
        #: compile_s / dispatches (cumulative).
        self.programs: dict[str, dict] = {}
        # Model-side device bytes for HBM reconciliation (the engine
        # sets these from its own exact accounting).
        self.param_bytes = 0
        self.kv_total_bytes = 0
        # Cumulative odometers (also mirrored to registry counters).
        self.total_flops = 0.0
        self.total_h2d = 0
        self.total_d2h = 0
        self.dispatch_totals: dict[str, int] = {}
        self.retraces = 0
        self.retrace_compile_est_s = 0.0
        # Rolling window: explicit popleft keeps O(1) running sums.
        self._ring: collections.deque[dict] = collections.deque()
        self._sums = {"dt_s": 0.0, "flops": 0.0, "bytes_accessed": 0.0,
                      "h2d_bytes": 0.0, "d2h_bytes": 0.0, "sync_s": 0.0,
                      "compute_est_s": 0.0, "host_stall_s": 0.0}
        self._ticks = 0
        # engine-thread scratch for the tick being accumulated
        self._pend = self._fresh_pend()
        self._last_step_ts: float | None = None
        self._last_poll_ts: float | None = None
        self.last_memory: dict | None = None
        # Instruments by LITERAL name (the HVD005 contract).  The
        # conditional gauges (serve.mfu, device.bytes_in_use, ...) are
        # minted only when their value is honestly known — an absent
        # gauge beats a fabricated zero.
        self._c_compiles = registry.counter("device.compiles")
        self._h_compile_s = registry.histogram("device.compile_s")
        self._c_flops = registry.counter("device.model_flops")
        self._c_h2d = registry.counter("device.h2d_bytes")
        self._c_d2h = registry.counter("device.d2h_bytes")
        self._g_headroom = registry.gauge("device.overlap_headroom_pct")
        registry.gauge("device.peak_flops_known").set(
            1 if self.peak_flops_known else 0)

    @staticmethod
    def _identify() -> tuple[str, str]:
        try:
            import jax
            d = jax.devices()[0]
            return d.platform, getattr(d, "device_kind", d.platform)
        except Exception as exc:  # noqa: BLE001 — telemetry never kills serving
            warnings.warn(f"device identification failed ({exc!r}); "
                          "telemetry continues with unknown platform",
                          RuntimeWarning, stacklevel=2)
            return "unknown", "unknown"

    def _fresh_pend(self) -> dict:
        return {"dispatches": {}, "flops": 0.0, "bytes_accessed": 0.0,
                "h2d_bytes": 0, "d2h_bytes": 0, "sync_s": 0.0,
                "compute_est_s": 0.0, "host_stall_s": 0.0}

    # -- cost model + compile ledger (engine init / bench attach) ----------

    def set_model_bytes(self, *, param_bytes: int,
                        kv_total_bytes: int) -> None:
        """Exact model-side device bytes, for HBM reconciliation."""
        self.param_bytes = int(param_bytes)
        self.kv_total_bytes = int(kv_total_bytes)

    def capture(self, name: str, jitfn: Any, *avals: Any) -> dict:
        """AOT-compile one pinned program from abstract avals and record
        its cost model.  ``jax.jit(...).lower()`` does NOT mint a jit
        call-cache entry, so capturing leaves ``compile_cache_sizes()``
        untouched.  The timed compile is the ledger sample — the same
        program's first real call pays the same cost again through the
        jit cache, and every sentry-detected retrace re-pays it.
        Capture failures degrade to a zeroed row (telemetry must never
        break serving)."""
        t0 = time.perf_counter()
        entry = {"flops": 0.0, "bytes_accessed": 0.0, "compile_s": 0.0,
                 "dispatches": 0}
        try:
            compiled = jitfn.lower(*avals).compile()
            entry["compile_s"] = time.perf_counter() - t0
            cost = normalize_cost_analysis(compiled.cost_analysis())
            entry["flops"] = float(cost.get("flops", 0.0) or 0.0)
            entry["bytes_accessed"] = float(
                cost.get("bytes accessed", 0.0) or 0.0)
        except Exception as exc:  # noqa: BLE001 — degrade, don't break serving
            entry["error"] = repr(exc)
            warnings.warn(
                f"device cost capture failed for {name!r} ({exc!r}); "
                "telemetry continues without its cost model",
                RuntimeWarning, stacklevel=2)
        self.programs[name] = entry
        self._c_compiles.inc()
        self._h_compile_s.observe(entry["compile_s"])
        self.metrics.event(
            "device.capture", program=name, flops=entry["flops"],
            bytes_accessed=entry["bytes_accessed"],
            compile_s=entry["compile_s"], platform=self.platform,
            device_kind=self.device_kind, n_devices=self.n_devices,
            peak_flops=self.peak_flops,
            peak_flops_known=self.peak_flops_known)
        return entry

    def on_retrace(self, grew: dict) -> None:
        """Charge sentry-detected mid-serve cache growth with the
        captured compile cost of each regrown program — the ledger's
        answer to "how much did that retrace cost us"."""
        for prog, (before, after) in grew.items():
            n = after - max(before, 1)
            if n <= 0:
                continue
            self.retraces += n
            self._c_compiles.inc(n)
            est = self.programs.get(prog, {}).get("compile_s", 0.0)
            self.retrace_compile_est_s += est * n

    # -- hot path (engine thread) ------------------------------------------

    def dispatch(self, name: str, h2d_bytes: int = 0) -> None:
        """One dispatch of a pinned program, with its host->device
        argument bytes (the arrays the engine materializes per call —
        persistent donated state transfers nothing)."""
        p = self._pend
        p["dispatches"][name] = p["dispatches"].get(name, 0) + 1
        self.dispatch_totals[name] = (
            self.dispatch_totals.get(name, 0) + 1)
        entry = self.programs.get(name)
        if entry is not None:
            p["flops"] += entry["flops"]
            p["bytes_accessed"] += entry["bytes_accessed"]
        p["h2d_bytes"] += h2d_bytes

    def on_sync(self, name: str, t0: float, t1: float,
                d2h_bytes: int = 0) -> tuple[float, float]:
        """Split one measured ``device_sync`` readback wait ``[t0, t1]``
        into (device-compute estimate, host stall) using the cost
        model's predicted device time for program ``name`` — predicted
        = flops / peak.  With no honest peak (CPU rehearsals) the split
        degenerates to all-compute: we cannot prove any stall, so none
        is claimed.  Returns ``(compute_est_s, host_stall_s)``."""
        sync_s = max(t1 - t0, 0.0)
        est = sync_s
        if self.peak_flops:
            entry = self.programs.get(name)
            if entry is not None and entry["flops"] > 0.0:
                est = min(entry["flops"] / self.peak_flops, sync_s)
        stall = sync_s - est
        p = self._pend
        p["d2h_bytes"] += d2h_bytes
        p["sync_s"] += sync_s
        p["compute_est_s"] += est
        p["host_stall_s"] += stall
        return est, stall

    def on_step(self, step: int) -> None:
        """Close the step's pending record: fold it into the rolling
        window, refresh the gauges/counters, poll HBM at the configured
        cadence, and emit one ``device.tick`` event."""
        now = time.perf_counter()
        dt = (now - self._last_step_ts
              if self._last_step_ts is not None else 0.0)
        self._last_step_ts = now
        p = self._pend
        self._pend = self._fresh_pend()
        rec = {"step": step, "dt_s": dt, "flops": p["flops"],
               "bytes_accessed": p["bytes_accessed"],
               "h2d_bytes": p["h2d_bytes"], "d2h_bytes": p["d2h_bytes"],
               "sync_s": p["sync_s"],
               "compute_est_s": p["compute_est_s"],
               "host_stall_s": p["host_stall_s"],
               "dispatches": p["dispatches"]}
        if len(self._ring) >= self.window:
            old = self._ring.popleft()
            for k in self._sums:
                self._sums[k] -= old[k]
        self._ring.append(rec)
        for k in self._sums:
            self._sums[k] += rec[k]
        self._ticks += 1
        self.total_flops += p["flops"]
        self.total_h2d += p["h2d_bytes"]
        self.total_d2h += p["d2h_bytes"]
        if p["flops"]:
            self._c_flops.inc(int(p["flops"]))
        if p["h2d_bytes"]:
            self._c_h2d.inc(p["h2d_bytes"])
        if p["d2h_bytes"]:
            self._c_d2h.inc(p["d2h_bytes"])
        win = self._sums
        if win["dt_s"] > 0.0:
            self._g_headroom.set(
                100.0 * win["compute_est_s"] / win["dt_s"])
            if self.peak_flops:
                # Minted only here: no honest peak, no MFU gauge.
                self.metrics.gauge("serve.mfu").set(
                    win["flops"] / win["dt_s"] / self.peak_flops)
        if win["bytes_accessed"] > 0.0:
            self.metrics.gauge("serve.arithmetic_intensity").set(
                win["flops"] / win["bytes_accessed"])
        if (self._last_poll_ts is None
                or now - self._last_poll_ts >= self.poll_s):
            self._last_poll_ts = now
            self.poll_memory()
        self.metrics.event("device.tick", **rec)

    def poll_memory(self) -> dict | None:
        """One ``memory_stats()`` poll.  Backends without it (CPU)
        return None: the gauges are never minted and ``last_memory``
        records the honest absence."""
        stats = None
        try:
            import jax
            stats = jax.devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — poll failures are absence, not errors
            stats = None
        if not stats:
            self.last_memory = {"available": False}
            return None
        mem = {"available": True,
               "bytes_in_use": int(stats.get("bytes_in_use", 0)),
               "peak_bytes_in_use": int(
                   stats.get("peak_bytes_in_use", 0)),
               "bytes_limit": int(stats.get("bytes_limit", 0))}
        self.last_memory = mem
        self.metrics.gauge("device.bytes_in_use").set(
            mem["bytes_in_use"])
        self.metrics.gauge("device.peak_bytes_in_use").set(
            mem["peak_bytes_in_use"])
        if mem["bytes_limit"] > 0:
            frac = mem["bytes_in_use"] / mem["bytes_limit"]
            self.metrics.gauge("device.hbm_used_fraction").set(frac)
        self.metrics.event("device.memory", **mem)
        return mem

    # -- reporting (any thread) --------------------------------------------

    def report(self) -> dict:
        """The ``/device`` payload: platform + peak provenance, the
        per-program cost table, the compile ledger, the rolling-window
        achieved numbers (MFU only when the peak is honest), and the
        HBM reconciliation when the backend reports memory."""
        ring = list(self._ring)
        return build_report(
            platform=self.platform, device_kind=self.device_kind,
            n_devices=self.n_devices, peak_flops=self.peak_flops,
            peak_flops_known=self.peak_flops_known,
            peak_source=self.peak_source,
            programs={k: dict(v, dispatches=self.dispatch_totals.get(
                k, 0)) for k, v in self.programs.items()},
            compiles=int(self._c_compiles.value),
            compile_total_s=float(self._h_compile_s.sum),
            retraces=self.retraces,
            retrace_compile_est_s=self.retrace_compile_est_s,
            ticks=self._ticks, window=self.window, ring=ring,
            memory=self.last_memory, param_bytes=self.param_bytes,
            kv_total_bytes=self.kv_total_bytes)


def build_report(*, platform: str, device_kind: str, n_devices: int,
                 peak_flops: float | None, peak_flops_known: bool,
                 peak_source: str | None, programs: dict, compiles: int,
                 compile_total_s: float, retraces: int,
                 retrace_compile_est_s: float, ticks: int, window: int,
                 ring: list, memory: dict | None, param_bytes: int,
                 kv_total_bytes: int) -> dict:
    """Assemble the report schema from already-collected records — the
    shared shape of the live :meth:`DeviceTelemetry.report` and the
    event-log replay (:func:`report_from_events`), so the two are
    field-for-field comparable.  Pure arithmetic over its inputs: no
    clocks, no entropy (the HVD010 contract for the replay path)."""
    sums = {k: 0.0 for k in ("dt_s", "flops", "bytes_accessed",
                             "h2d_bytes", "d2h_bytes", "sync_s",
                             "compute_est_s", "host_stall_s")}
    dispatches: dict[str, int] = {}
    for rec in ring:
        for k in sums:
            sums[k] += rec.get(k, 0.0)
        for prog, n in (rec.get("dispatches") or {}).items():
            dispatches[prog] = dispatches.get(prog, 0) + int(n)
    dt = sums["dt_s"]
    win: dict[str, Any] = {
        "n": len(ring),
        "elapsed_s": dt,
        "flops": sums["flops"],
        "bytes_accessed": sums["bytes_accessed"],
        "h2d_bytes": int(sums["h2d_bytes"]),
        "d2h_bytes": int(sums["d2h_bytes"]),
        "sync_s": sums["sync_s"],
        "compute_est_s": sums["compute_est_s"],
        "host_stall_s": sums["host_stall_s"],
        "dispatches": dict(sorted(dispatches.items())),
        "flops_per_s": sums["flops"] / dt if dt else 0.0,
        "overlap_headroom_pct": (100.0 * sums["compute_est_s"] / dt
                                 if dt else 0.0),
        "arithmetic_intensity": (
            sums["flops"] / sums["bytes_accessed"]
            if sums["bytes_accessed"] else 0.0),
        # honest: no peak, no MFU — the key is present (schema-stable)
        # but null, and the gauge side never mints at all.
        "mfu": (sums["flops"] / dt / peak_flops
                if peak_flops and dt else None),
    }
    out: dict[str, Any] = {
        "platform": platform,
        "device_kind": device_kind,
        "n_devices": n_devices,
        "peak_flops": peak_flops,
        "peak_flops_known": peak_flops_known,
        "peak_flops_source": peak_source,
        "programs": {k: dict(v) for k, v in sorted(programs.items())},
        "compiles": compiles,
        "compile_total_s": compile_total_s,
        "retraces": retraces,
        "retrace_compile_est_s": retrace_compile_est_s,
        "ticks": ticks,
        "window": window,
        "win": win,
        "memory": memory,
    }
    if memory and memory.get("available"):
        model = param_bytes + kv_total_bytes
        out["reconciliation"] = {
            "param_bytes": param_bytes,
            "kv_total_bytes": kv_total_bytes,
            "model_bytes": model,
            "hbm_bytes_in_use": memory["bytes_in_use"],
            "framework_overhead_bytes":
                memory["bytes_in_use"] - model,
        }
    return out


def report_from_events(events: list[dict],
                       window: int | None = None) -> dict:
    """Rebuild the ``/device`` report schema from ``device.capture`` /
    ``device.tick`` / ``device.memory`` event-log records — the replay
    path (``tools/device_report.py``).  Reads ONLY recorded fields:
    wall clocks or fresh polls here would make a replayed report
    disagree with the live one it must match (hvdlint HVD010 polices
    this via its DETERMINISM_SURFACES row)."""
    captures = [e for e in events if e.get("kind") == "device.capture"]
    ticks = [e for e in events if e.get("kind") == "device.tick"]
    mems = [e for e in events if e.get("kind") == "device.memory"]
    programs: dict[str, dict] = {}
    for e in captures:          # last capture per program wins
        programs[str(e.get("program"))] = {
            "flops": float(e.get("flops", 0.0)),
            "bytes_accessed": float(e.get("bytes_accessed", 0.0)),
            "compile_s": float(e.get("compile_s", 0.0)),
            "dispatches": 0,
        }
    for e in ticks:
        for prog, n in (e.get("dispatches") or {}).items():
            if prog in programs:
                programs[prog]["dispatches"] += int(n)
    head = captures[-1] if captures else {}
    peak = head.get("peak_flops")
    n_ticks = len(ticks)
    win_n = n_ticks if window is None else min(window, n_ticks)
    ring = [{k: e.get(k, 0.0) for k in
             ("step", "dt_s", "flops", "bytes_accessed", "h2d_bytes",
              "d2h_bytes", "sync_s", "compute_est_s", "host_stall_s")}
            | {"dispatches": e.get("dispatches") or {}}
            for e in ticks[-win_n:]] if win_n else []
    memory = None
    if mems:
        m = mems[-1]
        memory = {"available": True,
                  "bytes_in_use": int(m.get("bytes_in_use", 0)),
                  "peak_bytes_in_use": int(
                      m.get("peak_bytes_in_use", 0)),
                  "bytes_limit": int(m.get("bytes_limit", 0))}
    return build_report(
        platform=str(head.get("platform", "unknown")),
        device_kind=str(head.get("device_kind", "unknown")),
        n_devices=int(head.get("n_devices", 1)),
        peak_flops=peak,
        peak_flops_known=bool(head.get("peak_flops_known", False)),
        peak_source="replay" if peak is not None else None,
        programs=programs,
        compiles=len(captures),
        compile_total_s=sum(p["compile_s"] for p in programs.values()),
        retraces=0, retrace_compile_est_s=0.0,
        ticks=n_ticks, window=window if window is not None else win_n,
        ring=ring, memory=memory, param_bytes=0, kv_total_bytes=0)


def maybe_telemetry(registry: "metrics_mod.MetricsRegistry",
                    *, n_devices: int = 1) -> DeviceTelemetry | None:
    """Env factory: a plane when ``HVD_TPU_DEVICE_TELEMETRY=1``, else
    None (the engine's ``device_telemetry=None`` default routes here)."""
    if os.environ.get("HVD_TPU_DEVICE_TELEMETRY", "") != "1":
        return None
    return DeviceTelemetry(registry, n_devices=n_devices)
