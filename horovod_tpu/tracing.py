"""Causal distributed tracing: span trees across the serving fleet.

The profiler tiles *aggregate* tick time and ``metrics.Trace`` stamps
flat per-request timestamps, but neither can answer the Dapper-style
question: for THIS slow request, which causal chain of spans — router
admission, failover hops, replica queue, prefill chunks, decode —
actually bounded its latency?  This module is that plane:

* :class:`TraceContext` — a ``(trace_id, span_id)`` pair propagated end
  to end: loadgen client → router HTTP front door (a W3C
  ``traceparent``-style header or a ``trace`` JSON field) →
  :class:`~horovod_tpu.router.HttpReplica` hops → the replica pump →
  ``ServeEngine``.  Child span ids are *derived* (a keyed hash of
  ``trace_id || parent || name || seq``), never drawn from entropy, so
  replaying the same request produces the same tree bit-for-bit.
* **Deterministic head sampling** — :func:`sampled` hashes a seeded key
  (the request id) into [0, 1) and compares against
  ``HVD_TPU_TRACE_SAMPLE``.  No wall clock, no unseeded entropy: the
  decision is a pure function of ``(seed, key)``, which keeps HVD010
  green and the simfleet/chaos campaigns bit-deterministic with
  tracing enabled.
* :class:`Tracer` — emits ``trace.span`` / ``trace.span_open`` records
  through a :class:`~horovod_tpu.metrics.MetricsRegistry` event sink
  (landing in the rank-stamped, torn-line-tolerant EventLog) and keeps
  a bounded in-memory ring of recent closed spans for the monitor's
  live ``/traces`` endpoint.
* **Reconstruction** — :func:`build_forest` folds span records (event
  log replay or live scrape) into per-trace trees, degrading to
  *labeled* partial trees on damage: an orphaned child (parent record
  torn away) becomes an ``orphan`` root, a ``span_open`` with no close
  (crash) renders ``unclosed``; it never throws on torn input.
* **Critical path** — :func:`critical_path` walks one tree charging
  every instant of the root interval to the deepest span covering it
  (gaps between children are parent self-time), so the entries tile
  the root duration *exactly*; :func:`aggregate_critical_paths` folds
  many trees into a fleet-level "where does p99 time go" breakdown.

Timestamps on spans are ``time.monotonic`` seconds (the same clock the
engine's ``Trace`` stamps and — on Linux — the profiler's
``perf_counter`` intervals use), comparable within one process.  Spans
from different processes share the trace/span *ids* but not a clock
base; reconstruction clips children into their parent's interval so
cross-process trees stay renderable.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
from typing import Any, Iterable

__all__ = [
    "SPAN_KIND", "SPAN_OPEN_KIND", "TraceContext", "Tracer",
    "sampled", "trace_id_for", "child_span_id", "env_sample_fraction",
    "env_trace_seed", "build_forest", "critical_path",
    "aggregate_critical_paths",
]

#: Event-log record kinds spans persist under.
SPAN_KIND = "trace.span"
SPAN_OPEN_KIND = "trace.span_open"

_TWO64 = float(2 ** 64)


def _hash64(payload: str) -> int:
    """64-bit keyed hash used for both sampling and id derivation —
    blake2b, never ``hash()`` (PYTHONHASHSEED would break replay)."""
    return int.from_bytes(
        hashlib.blake2b(payload.encode(), digest_size=8).digest(), "big")


def env_sample_fraction() -> float:
    """``HVD_TPU_TRACE_SAMPLE`` as a fraction in [0, 1] (0 = off)."""
    raw = os.environ.get("HVD_TPU_TRACE_SAMPLE", "")
    try:
        f = float(raw) if raw else 0.0
    except ValueError:
        return 0.0
    return min(max(f, 0.0), 1.0)


def env_trace_seed() -> int:
    """``HVD_TPU_TRACE_SEED`` — the sampling/id-derivation seed."""
    raw = os.environ.get("HVD_TPU_TRACE_SEED", "")
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def sampled(key: Any, fraction: float, seed: int = 0) -> bool:
    """Deterministic head-sampling decision: a pure function of
    ``(seed, key)`` — the same request id samples identically on every
    run, every rank, and every journal replay (the HVD010 surface)."""
    if fraction <= 0.0:
        return False
    if fraction >= 1.0:
        return True
    return _hash64(f"{seed}:{key}") / _TWO64 < fraction


def trace_id_for(key: Any, seed: int = 0) -> str:
    """The 32-hex trace id a root keyed on ``key`` gets (derived, so a
    journal replay of the same request rejoins the same trace)."""
    return hashlib.blake2b(f"{seed}:{key}".encode(),
                           digest_size=16).hexdigest()


def child_span_id(trace_id: str, parent_id: str, name: str,
                  seq: int = 0) -> str:
    """16-hex span id derived from the causal position — no entropy, so
    re-deriving the same child (e.g. on a replay) collides on purpose
    and the forest dedupes it into one node."""
    return hashlib.blake2b(f"{trace_id}|{parent_id}|{name}|{seq}".encode(),
                           digest_size=8).hexdigest()


class TraceContext:
    """The propagated pair: which trace, and which span is the current
    causal parent.  Only *sampled* requests carry a context at all —
    unsampled is ``None`` everywhere, so the disabled plane costs one
    attribute test per hop."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id}, {self.span_id})"

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def child(self, name: str, seq: int = 0) -> "TraceContext":
        """Context whose span is a derived child of this one."""
        return TraceContext(
            self.trace_id,
            child_span_id(self.trace_id, self.span_id, name, seq))

    # -- wire formats -------------------------------------------------------

    def to_header(self) -> str:
        """W3C ``traceparent``-style header value (flags always 01 —
        an unsampled request has no context to serialize)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_header(cls, value: str | None) -> "TraceContext | None":
        """Parse a ``traceparent`` header; malformed or flag-00
        (unsampled) values degrade to ``None``, never raise."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split("-")
        if len(parts) != 4:
            return None
        _ver, tid, sid, flags = parts
        if len(tid) != 32 or len(sid) != 16:
            return None
        try:
            int(tid, 16), int(sid, 16)
        except ValueError:
            return None
        if flags == "00":
            return None
        return cls(tid, sid)

    def to_dict(self) -> dict:
        """The JSON wire field (rides ``request_to_json`` so
        ``HttpReplica`` hops forward it for free)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Any) -> "TraceContext | None":
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not (isinstance(tid, str) and isinstance(sid, str)
                and len(tid) == 32 and len(sid) == 16):
            return None
        return cls(tid, sid)

    # -- roots --------------------------------------------------------------

    @classmethod
    def root(cls, key: Any, name: str = "request",
             fraction: float | None = None,
             seed: int | None = None) -> "TraceContext | None":
        """Head-sampled root context for a new request keyed on ``key``
        (``None`` when the sampler says no)."""
        if fraction is None:
            fraction = env_sample_fraction()
        if seed is None:
            seed = env_trace_seed()
        if not sampled(key, fraction, seed):
            return None
        tid = trace_id_for(key, seed)
        return cls(tid, child_span_id(tid, "", name))


def count_sampled(metrics: Any) -> None:
    """Bump the root-sampling counter (one literal call site for the
    HVD005 table; every plane that mints a root calls through here)."""
    metrics.counter("trace.sampled").inc()


class Tracer:
    """Span emitter: persists ``trace.span`` records through a registry
    event sink (→ EventLog when one is attached) and keeps a bounded
    ring of recent closed spans for live scrapes.

    Emission is post-hoc — callers pass monotonic ``t0``/``t1`` stamps
    they already took (router tickets, engine ``Trace`` fields), so the
    tracer adds no clock reads to hot paths and virtual-clock drivers
    (simfleet) stamp spans off their injected clock."""

    _GUARDED_BY_LOCK = ("_ring",)

    def __init__(self, metrics: Any, ring: int = 1024):
        self.metrics = metrics
        self._lock = threading.Lock()
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=ring)
        self._c_spans = metrics.counter("trace.spans")

    def span_open(self, ctx: TraceContext, name: str, t0: float,
                  parent_id: str | None = None, **attrs: Any) -> None:
        """Durable evidence a span STARTED — a crash before the close
        record leaves an ``unclosed`` node in the forest instead of
        nothing."""
        self.metrics.event(
            SPAN_OPEN_KIND, trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_id=parent_id, name=name, t0=t0, attrs=attrs)

    def span(self, ctx: TraceContext, name: str, t0: float, t1: float,
             parent_id: str | None = None, **attrs: Any) -> None:
        """Emit one closed span ``[t0, t1]`` (monotonic seconds)."""
        rec = {"trace_id": ctx.trace_id, "span_id": ctx.span_id,
               "parent_id": parent_id, "name": name,
               "t0": t0, "t1": t1, "attrs": attrs}
        self._c_spans.inc()
        self.metrics.event(SPAN_KIND, **rec)
        with self._lock:
            self._ring.append(dict(rec, kind=SPAN_KIND))

    def recent(self) -> list[dict]:
        """Recent closed spans, oldest first (the ``/traces`` payload)."""
        with self._lock:
            return list(self._ring)


# ---------------------------------------------------------------------------
# Reconstruction: records -> forest -> critical path.
# ---------------------------------------------------------------------------


def build_forest(records: Iterable[dict]) -> dict[str, list[dict]]:
    """Fold span records into ``{trace_id: [root nodes]}``.

    Accepts the raw event-log record stream (non-span kinds are
    skipped) or a ``/traces`` scrape.  Damage degrades, never throws:

    * a close record supersedes its ``span_open`` (same span id);
      duplicate closes (journal-replay re-derivation) keep the last;
    * a ``span_open`` with no close becomes an ``unclosed`` node whose
      ``t1`` is ``None``;
    * a child whose parent record is missing (torn away, unsampled
      ancestor, foreign incarnation) is promoted to an ``orphan`` root
      of the same trace — the tree renders partial, labeled.

    Node schema: ``trace_id, span_id, parent_id, name, t0, t1, attrs,
    unclosed, orphan, children`` (children sorted by ``t0``).
    """
    nodes: dict[tuple[str, str], dict] = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind not in (SPAN_KIND, SPAN_OPEN_KIND):
            continue
        tid, sid = rec.get("trace_id"), rec.get("span_id")
        if not (isinstance(tid, str) and isinstance(sid, str)):
            continue
        t0 = rec.get("t0")
        if not isinstance(t0, (int, float)):
            continue
        t1 = rec.get("t1")
        closed = kind == SPAN_KIND and isinstance(t1, (int, float))
        prior = nodes.get((tid, sid))
        if prior is not None and not closed and not prior["unclosed"]:
            continue                    # an open never beats a close
        attrs = rec.get("attrs")
        nodes[(tid, sid)] = {
            "trace_id": tid,
            "span_id": sid,
            "parent_id": rec.get("parent_id"),
            "name": str(rec.get("name", "?")),
            "t0": float(t0),
            "t1": float(t1) if closed else None,
            "attrs": attrs if isinstance(attrs, dict) else {},
            "unclosed": not closed,
            "orphan": False,
            "children": [],
        }
    forest: dict[str, list[dict]] = {}
    for (tid, sid), node in sorted(nodes.items(),
                                   key=lambda kv: (kv[0][0],
                                                   kv[1]["t0"])):
        pid = node["parent_id"]
        parent = nodes.get((tid, pid)) if isinstance(pid, str) else None
        if parent is None or parent is node:
            node["orphan"] = parent is None and pid is not None
            forest.setdefault(tid, []).append(node)
        else:
            parent["children"].append(node)
    for roots in forest.values():
        for root in roots:
            stack = [root]
            while stack:
                n = stack.pop()
                n["children"].sort(key=lambda c: c["t0"])
                stack.extend(n["children"])
    return forest


def span_end(node: dict) -> float:
    """A node's effective end: its close stamp, or (unclosed) the
    latest end among descendants, or its own start."""
    best = node["t1"] if node["t1"] is not None else node["t0"]
    for ch in node["children"]:
        best = max(best, span_end(ch))
    return best


def critical_path(root: dict) -> list[dict]:
    """The blocking chain: every instant of the root interval charged
    to the deepest span covering it, so the entries' ``self_s`` sum to
    the root duration EXACTLY (gaps between children are parent
    self-time).  Children are clipped into their parent's interval —
    cross-process clock skew and torn ``t1``s degrade to clipped
    charges, never negative time or a throw.

    Returns ``[{name, span_id, t0, self_s}, ...]`` in time order.
    """
    entries: list[dict] = []

    def _charge(node: dict, lo: float, t: float) -> None:
        if t > lo:
            entries.append({"name": node["name"],
                            "span_id": node["span_id"],
                            "t0": lo, "self_s": t - lo})

    def _walk(node: dict, lo: float, hi: float) -> None:
        cur = lo
        for ch in node["children"]:
            c1 = span_end(ch) if ch["t1"] is None else ch["t1"]
            c0 = min(max(ch["t0"], cur), hi)
            c1 = min(max(c1, c0), hi)
            if c1 <= c0:
                continue
            _charge(node, cur, c0)
            _walk(ch, c0, c1)
            cur = c1
        _charge(node, cur, hi)

    hi = span_end(root)
    _walk(root, root["t0"], hi)
    return entries


def aggregate_critical_paths(roots: Iterable[dict]) -> dict:
    """Fleet-level breakdown: fold many trees' critical paths into
    per-span-name totals and shares — the "p99 requests spend 61% in
    replica_queue" view."""
    by_name: dict[str, dict] = {}
    total = 0.0
    n = 0
    for root in roots:
        n += 1
        for ent in critical_path(root):
            slot = by_name.setdefault(
                ent["name"], {"total_s": 0.0, "count": 0})
            slot["total_s"] += ent["self_s"]
            slot["count"] += 1
            total += ent["self_s"]
    for slot in by_name.values():
        slot["share"] = slot["total_s"] / total if total else 0.0
    return {"n_traces": n, "total_s": total,
            "by_name": dict(sorted(by_name.items(),
                                   key=lambda kv: -kv[1]["total_s"]))}
