"""Process / device model: ``init``, ``rank``, ``size``, mesh management.

TPU-native re-design of the reference's process model
(reference: horovod/common/__init__.py:51-154 ``HorovodBasics`` and the C API
horovod/common/operations.cc:2040-2095).

The reference runs ONE process per GPU under ``mpirun``; ``rank()`` names the
process and ``local_rank()`` pins its GPU.  On TPU the idiomatic model is
single-controller-per-host JAX: one Python process drives ``local_device_count``
chips and multi-host jobs use ``jax.distributed``.  The mapping is:

==================  ==========================================================
Horovod concept      TPU-native equivalent
==================  ==========================================================
world (all ranks)    all devices of the global ``Mesh`` (axis ``"hvd"``)
``size()``           global device count (chips == Horovod ranks)
``local_size()``     chips driven from THIS host (all processes sharing it)
``rank()``           global index of this process's first device
``local_rank()``     index of this process's first chip among the host's
                     chips — {0..nproc-1} for one-process-per-chip gangs,
                     0 for a single controller process
``cross_size()``     ``jax.process_count()``   (number of hosts)
``cross_rank()``     ``jax.process_index()``   (this host's index)
==================  ==========================================================

``local_rank``/``local_size`` follow the reference's per-host communicator
(operations.cc:1558-1590, ``MPI_COMM_TYPE_SHARED``): processes are grouped
by physical host.  The topology source is layered — the launcher's
``HOROVOD_TPU_LOCAL_RANK``/``HOROVOD_TPU_LOCAL_SIZE`` env when present
(it knows the per-host process layout it spawned), else a hostname
exchange over the ``jax.distributed`` key-value store for externally
launched multi-process gangs, else the single-controller identity.

Inside compiled SPMD code (``shard_map`` over the mesh) the *per-chip* rank is
``jax.lax.axis_index("hvd")`` — exposed here as :func:`axis_rank`.

Eager collectives (see :mod:`horovod_tpu.ops.eager`) operate on **rank-major**
arrays: a logical "tensor held by every rank" is represented as one
``jax.Array`` of shape ``[size(), *shape]`` sharded along axis 0, so each chip
holds its own slice — the single-controller analogue of per-process tensors.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.utils.env import EngineConfig

AXIS_NAME = "hvd"

# Analogue of CPU_DEVICE_ID (reference horovod/common/common.h:100): kept for
# API parity where a device id is reported for host-resident tensors.
CPU_DEVICE_ID = -1


class NotInitializedError(RuntimeError):
    """Raised when the API is used before ``init()``.

    Parity with the reference's "Horovod has not been initialized; use
    hvd.init()." ctypes-level errors (horovod/common/operations.cc:2047-2095).
    """


class HorovodInternalError(RuntimeError):
    """An ENVIRONMENTAL collective failure: the control plane broke, the
    engine was shut down underneath in-flight ops, or a peer vanished
    mid-negotiation — the failures :mod:`horovod_tpu.elastic` recovers
    from by re-initializing and replaying from the last committed state.

    Deterministic caller mistakes (shape/dtype mismatch between ranks,
    invalid arguments) stay plain ``ValueError``/``RuntimeError`` —
    retrying those would loop forever.  Name-parity with the exception
    Horovod's elastic mode keys on (its 0.20+ ``HorovodInternalError``;
    the 0.15.1 reference's closest analogue is the SHUT_DOWN_ERROR
    callback status, operations.cc:278-283)."""


class _State:
    """Global framework state — the analogue of ``HorovodGlobalState``
    (reference horovod/common/operations.cc:112-264), minus everything XLA
    already owns (streams, communicators, fusion buffers on device)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.initialized = False
        self.shut_down = False
        self.mesh: Mesh | None = None
        self.config: EngineConfig = EngineConfig()
        self.engine = None  # lazily created EagerEngine
        self.timeline = None  # lazily created Timeline
        self.profiler_active = False  # start_timeline(profiler_dir=...)
        # (local_rank, local_size) — resolved lazily, cached per init()
        self.local_topology: tuple[int, int] | None = None
        # The (devices, mesh) arguments of the last successful init(),
        # kept through shutdown() so an elastic in-process retry can
        # replay the SAME world: a bare re-init() would silently widen a
        # device-subset/custom-mesh world to all devices, changing
        # size() and the rank mapping mid-training.
        self.last_init_args: tuple | None = None


_state = _State()


_distributed_initialized = False


def _maybe_init_distributed() -> None:
    """Initialize multi-host JAX when a coordinator is configured.

    The reference calls ``MPI_Init_thread`` on its background thread
    (horovod/common/operations.cc:1505-1525); the TPU equivalent is
    ``jax.distributed.initialize()``, driven by env config rather than MPI.

    Must run before any other JAX call initializes the XLA backend, so the
    guard is a module flag — probing ``jax.process_count()`` here would
    itself initialize the backend and poison ``initialize()``.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return
    addr = os.environ.get("HOROVOD_TPU_COORDINATOR") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    nproc = os.environ.get("HOROVOD_TPU_NUM_PROCESSES")
    pid = os.environ.get("HOROVOD_TPU_PROCESS_ID")
    if addr and nproc and pid:
        try:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=int(nproc),
                process_id=int(pid),
            )
        except RuntimeError as e:
            raise RuntimeError(
                "horovod_tpu.init() could not start multi-host JAX: "
                f"{e}.  Call hvd.init() before any other JAX API so the "
                "distributed runtime can be set up first."
            ) from e
        _distributed_initialized = True


def _my_mesh_device_count(st: "_State") -> int:
    return sum(
        1 for d in st.mesh.devices.flat
        if d.process_index == jax.process_index()
    )


def _post_host_card(st: "_State") -> None:
    """Publish this process's ``hostname|mesh_device_count`` card to the
    ``jax.distributed`` key-value store so every peer can group ranks by
    physical host — the TPU-native stand-in for the reference's
    ``MPI_Comm_split_type(MPI_COMM_TYPE_SHARED)`` local communicator
    (reference operations.cc:1558-1590).  Posted once at ``init()`` AFTER
    the mesh is built (device-subset worlds advertise their mesh share,
    not the raw device count, so per-host local_size sums to size());
    reads happen lazily at the first ``local_rank()``/``local_size()``
    call.  Best-effort: without a distributed client (single process) or
    on a jax whose internal client API moved, the layered fallback in
    ``_local_topology`` takes over."""
    try:
        from jax._src.distributed import global_state

        client = global_state.client
        if client is None:
            return
        import socket

        client.key_value_set(
            f"horovod_tpu/hostcard/{jax.process_index()}",
            f"{socket.gethostname()}|{_my_mesh_device_count(st)}",
            allow_overwrite=True,  # re-init may change the mesh subset
        )
    except Exception:
        pass


def _negotiate_timeout_s() -> float:
    """Host-card negotiation deadline: ``HVD_TPU_NEGOTIATE_TIMEOUT_S``
    (seconds, default 60).  An unparsable value falls back to the
    default rather than wedging ``init()``."""
    raw = os.environ.get("HVD_TPU_NEGOTIATE_TIMEOUT_S", "60")
    try:
        return float(raw)
    except ValueError:
        import warnings

        warnings.warn(
            f"ignoring unparsable HVD_TPU_NEGOTIATE_TIMEOUT_S={raw!r}; "
            f"using the 60 s default",
            RuntimeWarning,
            stacklevel=2,
        )
        return 60.0


def _kv_topology() -> tuple[int, int] | None:
    """Group processes by host via the cards ``_post_host_card`` published.

    Returns ``(local_rank, local_size)`` in CHIP units: local_size is the
    total device count across the host's processes, local_rank the number
    of devices owned by lower-ranked processes on the same host — which
    reduces to process indices {0..n-1} under one-process-per-chip, and to
    (0, n_chips) under one-controller-per-host.

    One ``key_value_dir_get`` poll loop, not per-process blocking gets: a
    pod-scale gang fetches every card in O(1) round-trips per poll, and a
    peer that never posts (mixed versions) costs one shared deadline
    (``HVD_TPU_NEGOTIATE_TIMEOUT_S``, default 60) before the fallback —
    not a full stall per missing key.  A timed-out negotiation WARNS
    with the posted-vs-expected peer count before falling back, so a
    wrong local topology is diagnosable instead of silent."""
    try:
        import time

        from jax._src.distributed import global_state

        client = global_state.client
        n = jax.process_count()
        if client is None or n <= 1:
            return None
        from horovod_tpu import metrics as metrics_mod

        timeout_s = _negotiate_timeout_s()
        deadline = time.monotonic() + timeout_s
        while True:
            metrics_mod.DEFAULT.counter("hvd.negotiate_polls").inc()
            entries = client.key_value_dir_get("horovod_tpu/hostcard/")
            if len(entries) >= n:
                break
            if time.monotonic() >= deadline:
                import warnings

                metrics_mod.DEFAULT.counter(
                    "hvd.negotiate_timeouts").inc()
                metrics_mod.DEFAULT.event(
                    "hvd.negotiate_timeout", posted=len(entries),
                    expected=n, timeout_s=timeout_s)
                warnings.warn(
                    f"host-card negotiation timed out after "
                    f"{timeout_s:g}s: {len(entries)} of {n} peers "
                    f"posted host cards (set HVD_TPU_NEGOTIATE_TIMEOUT_S "
                    f"to adjust); falling back to launcher-env/"
                    f"single-host local topology",
                    RuntimeWarning,
                    stacklevel=3,
                )
                return None
            time.sleep(0.1)
        cards: dict[int, tuple[str, int]] = {}
        for key, raw in entries:
            host, ndev = raw.rsplit("|", 1)
            cards[int(key.rsplit("/", 1)[1])] = (host, int(ndev))
        me = jax.process_index()
        my_host = cards[me][0]
        before = sum(
            nd for i, (h, nd) in cards.items() if h == my_host and i < me
        )
        total = sum(nd for h, nd in cards.values() if h == my_host)
        return before, total
    except Exception:
        return None


def _local_topology(st: "_State") -> tuple[int, int]:
    """Resolve (local_rank, local_size), layered: launcher env (exact for
    the one-device-per-process model the launcher spawns — ignored when
    this process drives several chips, where process units would
    under-count) → KV-store host grouping → single-controller identity."""
    if st.local_topology is not None:
        return st.local_topology
    lr = os.environ.get("HOROVOD_TPU_LOCAL_RANK")
    ls = os.environ.get("HOROVOD_TPU_LOCAL_SIZE")
    topo = None
    if lr is not None and ls is not None and _my_mesh_device_count(st) == 1:
        topo = (int(lr), int(ls))
        world = st.mesh.devices.size
        if not (0 <= topo[0] < topo[1] <= world):
            # e.g. a launcher-spawned worker re-init()ed with a device
            # subset: the launcher's process-unit numbers no longer
            # describe this world (local_size would exceed size()).  Fall
            # through to the KV cards, which count mesh shares.
            topo = None
    if topo is None:
        topo = _kv_topology()
    if topo is None:
        topo = (0, _my_mesh_device_count(st))
    st.local_topology = topo
    return topo


def _honor_platform_env() -> None:
    """Make the launcher's platform choice actually win.

    Site-customize-installed TPU plugins may force ``jax_platforms`` via
    ``jax.config`` at interpreter start, which silently outranks the
    ``JAX_PLATFORMS`` env var — so ``horovodrun-tpu --cpu`` workers would
    still try to grab the TPU and hang if its tunnel is down.  The
    launcher therefore sets its OWN variable,
    ``HOROVOD_TPU_FORCE_PLATFORM``; only that is re-asserted here.  The
    ambient ``JAX_PLATFORMS`` is deliberately NOT: it may predate the
    process from the surrounding environment, and re-asserting it would
    override a user's explicit in-script ``jax.config.update``."""
    want = os.environ.get("HOROVOD_TPU_FORCE_PLATFORM")
    if not want:
        return
    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass


def init(
    devices: Sequence[jax.Device] | None = None,
    mesh: Mesh | None = None,
    comm=None,
) -> None:
    """Initialize the framework.  Analogue of ``hvd.init()``
    (reference horovod/common/__init__.py:58-84 → operations.cc:2011-2029).

    Args:
      devices: optional subset of devices to form the world (the analogue of
        the reference's ``init(comm=[ranks])`` rank-subset form).  Defaults to
        all devices.
      mesh: optional pre-built 1-D mesh whose single axis becomes the Horovod
        world.  Overrides ``devices``.
      comm: reference-parity spelling of the subset form: a list of ints
        selects those ranks' chips — ``init(comm=[0, 2])`` ≡
        ``init(devices=[jax.devices()[0], jax.devices()[2]])``.  An mpi4py
        communicator is not a TPU concept (there is no MPI runtime to
        share); passing one raises with that explanation.
    """
    if comm is not None:
        if devices is not None or mesh is not None:
            raise ValueError("init(): pass comm= or devices=/mesh=, not both")
        import numbers

        if not (isinstance(comm, (list, tuple)) and comm and all(
            isinstance(r, numbers.Integral) and not isinstance(r, bool)
            for r in comm
        )):
            raise TypeError(
                "init(comm=...) takes a non-empty list of int ranks on "
                "TPU.  MPI communicators don't exist here — the process "
                "world comes from jax.distributed (the launcher sets it "
                "up); for a rank-subset world pass the rank list, for "
                "subset COLLECTIVES on a full world use hvd.ProcessSet."
            )
        comm = [int(r) for r in comm]  # numpy integers welcome
    with _state.lock:
        if _state.initialized:
            return
        _honor_platform_env()
        _maybe_init_distributed()
        if comm is not None:
            # Resolve ranks only AFTER the platform pin and the
            # jax.distributed bring-up: jax.devices() commits the XLA
            # backend, and calling it first would poison both (the
            # invariant _maybe_init_distributed documents).
            all_devs = jax.devices()
            bad = [r for r in comm if not 0 <= r < len(all_devs)]
            if bad:
                raise ValueError(
                    f"init(comm={list(comm)}): ranks {bad} outside "
                    f"[0, {len(all_devs)})"
                )
            devices = [all_devs[r] for r in comm]
        if mesh is not None:
            if len(mesh.axis_names) != 1:
                raise ValueError(
                    "init(mesh=...) expects a 1-D mesh; for multi-axis "
                    "parallelism build your own mesh and use "
                    "horovod_tpu.ops in-graph collectives directly."
                )
            _state.mesh = Mesh(mesh.devices, (AXIS_NAME,))
        else:
            devs = list(devices) if devices is not None else jax.devices()
            import numpy as np

            _state.mesh = Mesh(np.asarray(devs), (AXIS_NAME,))
        _state.config = EngineConfig.from_env()
        _state.local_topology = None
        if mesh is not None:
            _state.last_init_args = (None, mesh)
        else:
            # Record the MATERIALIZED list, not the caller's argument: a
            # one-shot iterable is already exhausted by the list() above.
            _state.last_init_args = (
                tuple(devs) if devices is not None else None, None)
        _post_host_card(_state)
        _state.initialized = True
        _state.shut_down = False
    # Pin the rank identity stamped on event-log records / state dumps
    # (outside the lock: rank() re-enters _require_init's read path).
    from horovod_tpu import metrics as metrics_mod
    metrics_mod.set_rank(rank())
    atexit.register(shutdown)


def shutdown() -> None:
    """Shut the framework down.  Analogue of ``hvd.shutdown()``
    (reference horovod/common/__init__.py atexit hook → operations.cc:2046).

    Drains the eager engine (all outstanding handles complete or error) and
    releases global state; idempotent.
    """
    with _state.lock:
        if not _state.initialized or _state.shut_down:
            return
        engine, _state.engine = _state.engine, None
        timeline, _state.timeline = _state.timeline, None
        profiling, _state.profiler_active = _state.profiler_active, False
        _state.shut_down = True
        _state.initialized = False
        _state.mesh = None
        _state.local_topology = None
    if profiling:
        # A start_timeline(profiler_dir=...) window left open at shutdown
        # must still finalize the XLA profile (a dangling trace would make
        # the next start_trace raise).
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
    if engine is not None:
        engine.shutdown()
    if timeline is not None:
        timeline.close()
    from horovod_tpu import metrics as metrics_mod
    metrics_mod.set_rank(None)


def is_initialized() -> bool:
    return _state.initialized


def _require_init() -> _State:
    if not _state.initialized:
        raise NotInitializedError(
            "horovod_tpu has not been initialized; use horovod_tpu.init()."
        )
    return _state


def mesh() -> Mesh:
    """The world mesh (single axis ``"hvd"``, one entry per chip)."""
    return _require_init().mesh


def config() -> EngineConfig:
    return _require_init().config


def size() -> int:
    """Total number of chips in the world — the Horovod world size
    (reference operations.cc:2063-2067)."""
    return _require_init().mesh.devices.size


def local_size() -> int:
    """Chips driven from this HOST — all its processes together
    (reference operations.cc:2069-2073: the per-host communicator's size).
    One-process-per-chip gangs see the host's process count; a single
    controller sees its own device count.  Topology resolution order is
    documented in the module docstring."""
    return _local_topology(_require_init())[1]


def rank() -> int:
    """Global index of this process's first device
    (reference operations.cc:2051-2055; see module docstring for mapping)."""
    st = _require_init()
    for i, d in enumerate(st.mesh.devices.flat):
        if d.process_index == jax.process_index():
            return i
    return 0


def local_rank() -> int:
    """Index of this process's first chip among the host's chips
    (reference operations.cc:2057-2061: rank in the per-host communicator).
    {0..nproc-1} under the one-process-per-chip model the torch frontend
    uses — so reference-style per-host logic ("first process on host",
    data staggering, per-host caching) ports unchanged; 0 for a single
    controller process (device pinning is owned by the TPU runtime)."""
    return _local_topology(_require_init())[0]


def cross_size() -> int:
    """Number of hosts (the reference's cross-communicator size,
    operations.cc:1558-1590)."""
    _require_init()
    return jax.process_count()


def cross_rank() -> int:
    """This host's index (reference cross-communicator rank)."""
    _require_init()
    return jax.process_index()


def mpi_threads_supported() -> bool:
    """Parity shim (reference operations.cc:2089-2095).  There is no MPI in
    the TPU runtime; multi-controller coordination is always thread-safe."""
    _require_init()
    return True


def axis_rank():
    """Per-chip rank inside compiled SPMD code: ``lax.axis_index("hvd")``."""
    return jax.lax.axis_index(AXIS_NAME)


# ---------------------------------------------------------------------------
# Rank-major helpers: build / inspect the eager representation.
# ---------------------------------------------------------------------------


def rank_sharding() -> NamedSharding:
    """Sharding that splits axis 0 over ranks (eager rank-major layout)."""
    return NamedSharding(mesh(), P(AXIS_NAME))


def replicated_sharding() -> NamedSharding:
    return NamedSharding(mesh(), P())


def from_per_rank(values) -> jax.Array:
    """Stack one-per-rank host values into a rank-major sharded array.

    The single-controller analogue of "each MPI process holds its tensor":
    ``values`` is a sequence of ``size()`` equal-shaped arrays; the result has
    shape ``[size(), *shape]`` with shard *i* resident on chip *i*.
    """
    import jax.numpy as jnp

    n = size()
    if len(values) != n:
        raise ValueError(f"expected {n} per-rank values, got {len(values)}")
    stacked = jnp.stack([jnp.asarray(v) for v in values])
    return jax.device_put(stacked, rank_sharding())


def per_rank(fn) -> jax.Array:
    """Build a rank-major array from ``fn(rank) -> array``  (test helper for
    the reference's rank-dependent tensors, test/test_tensorflow.py:56-86)."""
    return from_per_rank([fn(r) for r in range(size())])
