"""Shared-prefix KV cache: a radix index over ref-counted paged blocks.

Real serving fleets see the same system/few-shot prompt prefix on most
requests; recomputing its prefill per admission is the dominant
avoidable cost in the continuous-batching engine.  PagedAttention
(Kwon et al., vLLM SOSP '23) showed KV can be shared across requests at
block granularity; RadixAttention (Zheng et al., SGLang) showed an
automatic radix-tree index over token prefixes makes the sharing
transparent — no client-side prefix handles, just longest-prefix match
on admission.  This module is both, mapped onto the existing
:class:`~horovod_tpu.models.llama.PagedKVCache` block tables:

* **Full, immutable blocks only.**  A physical block enters the index
  only once every one of its ``block_size`` positions holds the KV of a
  known token path starting at sequence position 0.  Indexed blocks are
  never written again — a row's write frontier is kept strictly inside
  its own private blocks (see COW below) — so sharing needs no device
  copies and no new compiled programs: a cache hit writes different
  block-table *data* through the engine's existing ``_set_row``
  program.

* **Radix tree keyed by token chunks.**  Each node is one full block;
  its edge key is the ``block_size``-token tuple the block holds, so a
  root-to-node path spells the exact token prefix (and therefore the
  exact rotary positions) the node's KV was computed from.  Longest
  prefix match walks the tree chunk by chunk; admission maps the hit
  blocks straight into the new slot's block-table row and chunked
  prefill starts at the first uncached token.

* **Reference counts + LRU release-to-cache.**  Every block a live row
  maps carries a reference (:class:`~horovod_tpu.models.llama.BlockPool`);
  retirement *releases to cache* instead of freeing — zero-ref indexed
  blocks park in LRU order and are reclaimed leaf-first when admission
  runs short, always BEFORE any live decoding row is preempted.

* **Copy-on-write tail.**  The block containing a request's write
  frontier must be private.  A match is therefore capped at
  ``(len(prompt) - 1) // block_size`` blocks: at least the prompt's
  last token always re-prefills (its logits seed decoding — KV reuse
  alone can't produce them), and when the cap bites (prompt ends
  exactly on a block boundary, fully cached), the final shared block is
  "copied" by *recomputing* its tokens into a fresh private block —
  deterministic prefill makes the copy bit-identical, and the shared
  original is never touched.  Divergent continuations after a common
  prefix therefore never interfere: each row appends into its own tail.

The whole subsystem is host-side bookkeeping; parity is exact by
construction (same KV values at the same positions, same programs), and
is pinned by ``tests/test_prefix_cache.py`` against cache-off runs.

Because it only ever deals in *logical* block ids, the index is also
**shard-agnostic**: under tensor-parallel serving
(``ServeEngine(tp_size=N)``) the paged pool is head-split across the
``('tp',)`` mesh and one block id addresses the same slot of every
chip's head slice, so matching, release-to-cache, COW, and eviction
work over a sharded pool unchanged (pinned by
``tests/test_serving_tp.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import sys
from typing import Iterable

from horovod_tpu import metrics as metrics_mod
from horovod_tpu.models.llama import BlockPool


def _update_chunk(h: "hashlib._Hash", chunk: Iterable[int]) -> None:
    """Fold one block-size token chunk into a running path digest.
    Token ids render as decimal bytes with unambiguous separators, so
    the encoding is stable across processes and Python versions (unlike
    the salted builtin ``hash``)."""
    h.update(b"|")
    for t in chunk:
        h.update(str(int(t)).encode())
        h.update(b",")


def chunk_path_digests(tokens: Iterable[int], block_size: int,
                       max_chunks: int | None = None) -> list[str]:
    """Digest every block-aligned prefix of ``tokens``.

    Entry ``i`` digests ``tokens[:(i + 1) * block_size]`` — exactly the
    token path a depth-``i + 1`` radix node spells — so membership of a
    prompt's digests in a cache's :meth:`RadixPrefixCache.key_digest`
    summary measures the longest indexed prefix WITHOUT shipping the
    tokens themselves.  Incremental blake2b keeps the whole list one
    pass over the prompt."""
    tokens = list(tokens)
    h = hashlib.blake2b(digest_size=8)
    n = len(tokens) // block_size
    if max_chunks is not None:
        n = min(n, max_chunks)
    out: list[str] = []
    for i in range(n):
        _update_chunk(h, tokens[i * block_size:(i + 1) * block_size])
        out.append(h.hexdigest())
    return out


@dataclasses.dataclass
class RadixNode:
    """One full, immutable KV block on the prefix tree.  ``key`` is the
    block's token chunk (the edge label from ``parent``); the
    root-to-here key concatenation is the token path whose KV the block
    holds at positions ``[depth * block_size, (depth+1) * block_size)``."""

    block: int
    key: tuple[int, ...]
    parent: "RadixNode | None"
    children: dict[tuple[int, ...], "RadixNode"] = dataclasses.field(
        default_factory=dict)


class RadixPrefixCache:
    """The prefix index over a :class:`BlockPool`.

    The cache never allocates: callers hand it blocks that are already
    written (``insert``), and it hands back shared blocks with a
    reference taken (``acquire``).  Eviction (``evict``) walks zero-ref
    LRU blocks leaf-first and returns them to the pool's free list;
    interior nodes become leaves as their children go, so a cold
    subtree drains oldest-leaf-first without ever orphaning a path.

    ``stats``: cumulative counters — ``hits`` (acquire calls matching
    >= 1 block), ``misses``, ``blocks_reused``, ``tokens_skipped``
    (``blocks_reused * block_size``: prefill positions admission did
    not recompute), ``inserted_blocks``, ``evicted_blocks``.  Each is
    mirrored into ``metrics`` as a ``prefix.<name>`` counter
    (:mod:`horovod_tpu.metrics`); the default ``NULL`` registry makes a
    standalone cache silent, while :class:`ServeEngine` passes its own
    registry so the mirrors land in the engine's scrape.
    """

    def __init__(self, pool: BlockPool, block_size: int,
                 metrics: "metrics_mod.MetricsRegistry | None" = None):
        if block_size < 1:
            raise ValueError(f"block_size {block_size} must be >= 1")
        self.pool = pool
        self.block_size = block_size
        self.metrics = metrics if metrics is not None else metrics_mod.NULL
        self._root = RadixNode(block=0, key=(), parent=None)
        self._nodes: dict[int, RadixNode] = {}     # block -> node
        self.stats = {"hits": 0, "misses": 0, "blocks_reused": 0,
                      "tokens_skipped": 0, "inserted_blocks": 0,
                      "evicted_blocks": 0}
        self._digest_cache: dict | None = None

    def _bump(self, key: str, n: int = 1) -> None:
        self.stats[key] += n
        self.metrics.counter("prefix." + key).inc(n)

    # -- introspection -----------------------------------------------------

    def indexed_blocks(self) -> int:
        return len(self._nodes)

    def approx_footprint_bytes(self) -> int:
        """Approximate host bytes the radix index holds (the
        ``mem.prefix_index_bytes`` gauge): per node its object, its
        token-chunk key tuple, and its children dict, plus the block->
        node map — shallow ``sys.getsizeof`` sums, a leak-spotting
        trend line rather than an exact audit."""
        total = sys.getsizeof(self._nodes)
        for node in [self._root, *self._nodes.values()]:
            total += (sys.getsizeof(node) + sys.getsizeof(node.key)
                      + sys.getsizeof(node.children))
        return total

    def key_digest(self, max_paths: int = 256) -> dict:
        """Bounded summary of the index for cache-aware routing.

        Returns ``{"block_size", "indexed_blocks", "n_paths",
        "truncated", "paths"}`` where ``paths`` holds up to
        ``max_paths`` hex digests of root-to-node token paths
        (:func:`chunk_path_digests` encoding), breadth-first — shallow
        prefixes (the system prompts a router cares about) always make
        the cut; deep divergent tails are what truncation drops.  A
        router matches a prompt by digesting its own chunks and finding
        the deepest digest present here; no token ever leaves the
        replica.  Cost is one ``blake2b.copy()`` + one chunk hash per
        emitted path, so the summary is cheap enough to ride every
        ``metrics_snapshot()``.

        The monitor serves ``/snapshot`` from its own HTTP thread while
        the engine thread inserts/evicts nodes, so a scrape can land
        mid-mutation and the walk can see a ``children`` dict change
        size under it.  The walk retries on that ``RuntimeError`` and,
        if the tree never holds still, falls back to the last complete
        summary — staleness is benign for routing (one suboptimal
        placement), a crashed scrape is not."""
        for _ in range(4):
            try:
                summary = self._key_digest_walk(max_paths)
            except RuntimeError:        # tree mutated mid-walk
                continue
            self._digest_cache = summary
            return summary
        stale = self._digest_cache
        if stale is not None:
            return dict(stale)
        return {"block_size": self.block_size,
                "indexed_blocks": len(self._nodes), "n_paths": 0,
                "truncated": len(self._nodes) > 0, "paths": []}

    def _key_digest_walk(self, max_paths: int) -> dict:
        paths: list[str] = []
        base = hashlib.blake2b(digest_size=8)
        q: "collections.deque[tuple[RadixNode, hashlib._Hash]]" = \
            collections.deque(
                (child, base) for child in self._root.children.values())
        while q and len(paths) < max_paths:
            node, parent_h = q.popleft()
            h = parent_h.copy()
            _update_chunk(h, node.key)
            paths.append(h.hexdigest())
            for c in node.children.values():
                q.append((c, h))
        return {
            "block_size": self.block_size,
            "indexed_blocks": len(self._nodes),
            "n_paths": len(paths),
            "truncated": len(self._nodes) > len(paths),
            "paths": paths,
        }

    def __contains__(self, block: int) -> bool:
        return block in self._nodes

    def path_blocks(self, tokens: list[int]) -> list[int]:
        """Longest-prefix match WITHOUT taking references (read-only
        peek, for tests/dumps): block ids covering the longest fully
        indexed chunk path of ``tokens``."""
        return [n.block for n in self._walk(tokens, len(tokens))]

    # -- the hit path ------------------------------------------------------

    def _walk(self, tokens: list[int], max_tokens: int) -> list[RadixNode]:
        bs = self.block_size
        node, out = self._root, []
        for i in range(min(len(tokens), max_tokens) // bs):
            child = node.children.get(tuple(tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            out.append(child)
            node = child
        return out

    def acquire(self, tokens: list[int]) -> list[int]:
        """Longest-prefix match for an admission, references taken.

        Returns the physical blocks covering the longest indexed chunk
        path of ``tokens[:-1]`` — capped one token short so the block
        holding the write frontier is always private (the COW rule: a
        full hit recomputes its final chunk into a fresh block rather
        than mutating the shared one).  Each returned block is
        incref'd — pinned against eviction — until ``release``."""
        matched = self._walk(tokens, max(len(tokens) - 1, 0))
        blocks = [n.block for n in matched]
        for b in blocks:
            self.pool.incref(b)
        if blocks:
            self._bump("hits")
            self._bump("blocks_reused", len(blocks))
            self._bump("tokens_skipped", len(blocks) * self.block_size)
        else:
            self._bump("misses")
        return blocks

    def release(self, blocks: Iterable[int]) -> None:
        """Drop one reference per block (row retirement / requeue /
        failed admission).  Indexed blocks reaching zero references
        park in the pool's LRU cache; private ones free."""
        for b in blocks:
            self.pool.decref(b)

    # -- the insert path ---------------------------------------------------

    def insert(self, tokens: list[int], blocks: list[int],
               frontier: int) -> int:
        """Register a retiring row's full blocks (release-to-cache).

        ``tokens`` is the row's complete token path from position 0
        (replay prompt + emitted output), ``blocks`` its physical
        blocks in table order, ``frontier`` how many positions of the
        path are actually written (<= len(tokens)).  Every fully
        written block extends the tree; a chunk path that already has a
        node keeps the incumbent block (the retiring row's duplicate
        stays unindexed and frees on release).  Returns how many blocks
        were newly indexed.  The caller still owns its references —
        call ``release`` afterwards."""
        bs = self.block_size
        node, added = self._root, 0
        for i in range(min(frontier, len(tokens)) // bs):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = RadixNode(block=blocks[i], key=key, parent=node)
                node.children[key] = child
                self._nodes[blocks[i]] = child
                self.pool.mark_indexed(blocks[i])
                added += 1
            node = child
        if added:
            self._bump("inserted_blocks", added)
        return added

    # -- eviction ----------------------------------------------------------

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` cached blocks, LRU leaf-first.

        Only zero-reference leaves are evictable (an interior node's
        block must outlive its descendants or their paths would dangle;
        a referenced block is pinned by live rows).  Evicting a leaf
        can turn its parent into a leaf, so the walk repeats until the
        quota is met or a full pass frees nothing.  Returns the number
        of blocks returned to the free list."""
        freed = 0
        while freed < n_blocks:
            progress = False
            for b in self.pool.lru_blocks():          # oldest first
                node = self._nodes[b]
                if node.children:
                    continue                          # interior: skip
                del node.parent.children[node.key]
                del self._nodes[b]
                self.pool.drop_indexed(b)             # -> free list
                freed += 1
                progress = True
                if freed >= n_blocks:
                    break
            if not progress:
                break
        if freed:
            self._bump("evicted_blocks", freed)
            self.metrics.event("prefix.evict", freed=freed,
                               indexed=len(self._nodes))
        return freed

    # -- debugging ---------------------------------------------------------

    def check_consistency(self) -> None:
        """Structural invariants (the env-gated debug walk): every
        indexed block has a tree node reachable from the root, parents
        of every node are indexed (no dangling paths), and zero-ref
        indexed blocks are exactly the pool's LRU set."""
        seen: dict[int, RadixNode] = {}
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.block in seen:
                raise AssertionError(
                    f"block {n.block} appears at two tree positions")
            seen[n.block] = n
            stack.extend(n.children.values())
        if seen.keys() != self._nodes.keys():
            raise AssertionError(
                f"node map out of sync with tree: map-only="
                f"{set(self._nodes) - set(seen)} tree-only="
                f"{set(seen) - set(self._nodes)}")
        lru = set(self.pool.lru_blocks())
        zero_ref = {b for b in seen if self.pool.refcount(b) == 0}
        if lru != zero_ref:
            raise AssertionError(
                f"LRU set {lru} != zero-ref indexed set {zero_ref}")
