"""Multi-replica serving front door: prefix-locality routing, admission
control, and goodput-driven load shedding over N ``ServeEngine``\\ s.

PRs 1-8 finished the single-rank serving core — engine, faults, prefix
cache, observability, profiler, speculation.  This module is ROADMAP
open item 2, the scale-out layer: **many engines, one door**.  A
stdlib-only HTTP server (:class:`RouterServer`, the ``monitor.py``
threading-HTTP pattern) fronts a fleet of replica backends and decides,
per request, *which* replica serves it:

* **Pluggable routing** via :class:`RoutingPolicy` — the same seam
  shape as PR 8's :class:`~horovod_tpu.scheduling.SchedulerPolicy`:
  policies see read-only fleet state and return a choice, never mutate
  scheduler internals, never touch device programs.
  :class:`RoundRobinPolicy` cycles, :class:`LeastLoadedPolicy` picks
  the emptiest replica (fewest in-flight, best goodput), and the
  headline :class:`PrefixAffinityPolicy` routes SGLang-style by
  **cache locality**: the router keeps a :class:`ShadowPrefixIndex`
  per replica — a bounded set of radix *path digests*, fed both by its
  own routing decisions and by each replica's
  :meth:`~horovod_tpu.prefix_cache.RadixPrefixCache.key_digest`
  summary off ``/snapshot`` — and sends each request to the replica
  sharing the longest cached prefix, falling back to least-loaded past
  a load-imbalance threshold (``HVD_TPU_ROUTER_IMBALANCE``).  No token
  ever leaves a replica: digests are stable blake2b chunk hashes
  (:func:`~horovod_tpu.prefix_cache.chunk_path_digests`).

* **Admission control on the observability plane.**  A poller thread
  probes each replica (in-process :class:`LocalReplica` view, or HTTP
  ``/snapshot`` + ``/healthz`` for :class:`HttpReplica`); when fleet
  goodput or the free-KV fraction drops below the
  ``HVD_TPU_ROUTER_MIN_GOODPUT`` / ``HVD_TPU_ROUTER_MIN_FREE_KV``
  floors the router sheds new work with ``REJECTED`` — the *same*
  terminal status contract as the engine's own queue-overflow shed and
  (since this PR) its malformed-request rejection, so a client checks
  one field no matter which layer said no.

* **Failover by replay.**  A replica death (the ``serve.router``
  fault site in the :class:`LocalReplica` pump, repeated probe
  failures for HTTP replicas — ``HVD_TPU_ROUTER_PROBE_FAILS``
  consecutive, and an HTTP replica rejoins when probes turn healthy
  again) marks it dead and re-enqueues its in-flight requests to
  survivors from the full original prompt.  Greedy decode is
  deterministic (scheduler invariant 2, PR 2), so the failed-over
  output is **bit-identical** to an uninterrupted run — mid-stream
  replica loss is invisible in the tokens, visible only in
  ``router.failovers``.  Replays per request are capped
  (``HVD_TPU_ROUTER_MAX_FAILOVERS``): a poison request that kills
  every pump it touches fails terminally instead of walking the whole
  fleet dead.

* **Crash durability** (PR 10).  ``HVD_TPU_ROUTER_JOURNAL=<path>``
  arms an append-only JSONL request journal (torn-line-tolerant — the
  :class:`~horovod_tpu.metrics.EventLog` reader idiom): one ``accept``
  record as a request is placed, one ``terminal`` record as it
  finishes.  A restarted router replays every accept with no terminal
  (:meth:`RouterServer.replay_journal` — greedy determinism makes the
  replayed tokens bit-identical to what the lost incarnation would
  have produced), and a client-supplied **idempotency key** makes
  retries exactly-once: a duplicate key returns the journaled result
  without touching a replica.  :meth:`RouterServer.stop` now drains —
  bounded by ``HVD_TPU_ROUTER_DRAIN_S`` — instead of abandoning pump
  threads with work queued; undrained requests fail terminally but
  keep their journal accept, so a restart replays them.  Replica
  *respawn* (a dead :class:`LocalReplica` coming back) lives one layer
  up in :class:`~horovod_tpu.supervisor.ReplicaSupervisor`, which
  rides :meth:`RouterServer.poll_now` and commits each respawn through
  :meth:`RouterServer.replace_replica`.

Everything is host-side bookkeeping: the router never allocates device
memory, never adds a jit signature, and works against replicas it can
only see through HTTP.  ``router.*`` metrics land in the router's own
registry (scraped at ``GET /metrics``); per-replica detail that
Prometheus names can't carry (the registry has no labels) is JSON at
``GET /replicas``.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Sequence

from horovod_tpu import faults as faults_mod
from horovod_tpu import metrics as metrics_mod
from horovod_tpu import tracing as tracing_mod
from horovod_tpu.monitor import env_float
from horovod_tpu.prefix_cache import chunk_path_digests
from horovod_tpu.serving import (FAILED, OK, REJECTED, Request,
                                 RequestResult)

# ---------------------------------------------------------------------------
# Shadow prefix index: what the router believes each replica has cached.
# ---------------------------------------------------------------------------


class ShadowPrefixIndex:
    """A bounded, token-free mirror of one replica's radix index.

    Holds hex digests of root-to-node chunk paths
    (:func:`~horovod_tpu.prefix_cache.chunk_path_digests` encoding).
    Two feeds keep it warm: :meth:`observe` digests every prompt the
    router sends to the replica (optimistic — the replica will cache it
    on retirement), and :meth:`load` merges the replica's own
    ``key_digest()`` summary from ``/snapshot`` (authoritative for what
    actually survived admission and eviction).  Matching walks a
    prompt's digests shallow-to-deep and stops at the first absent one,
    so a match is always a *contiguous* cached prefix — exactly what
    the engine's longest-prefix admission can reuse.

    The index is bounded FIFO at ``max_paths`` digests; staleness is
    benign in both directions (a phantom path costs one suboptimal
    route, a missing one costs one missed affinity hit).  Instances are
    mutated only under the owning router's lock — no lock of their own.
    """

    def __init__(self, block_size: int = 0, max_paths: int = 4096):
        self.block_size = block_size
        self.max_paths = max_paths
        self._digests: set[str] = set()
        self._order: collections.deque[str] = collections.deque()

    def _add(self, digest: str) -> None:
        if digest in self._digests:
            return
        self._digests.add(digest)
        self._order.append(digest)
        while len(self._order) > self.max_paths:
            self._digests.discard(self._order.popleft())

    def observe(self, tokens: Sequence[int]) -> None:
        """Optimistically index a prompt the router just routed here."""
        if self.block_size < 1:
            return
        for d in chunk_path_digests(tokens, self.block_size):
            self._add(d)

    def load(self, summary: dict | None) -> None:
        """Merge a replica ``key_digest()`` summary (adopts its
        ``block_size`` when the shadow doesn't know one yet)."""
        if not summary:
            return
        bs = summary.get("block_size", 0)
        if self.block_size < 1 and bs >= 1:
            self.block_size = bs
        for d in summary.get("paths", ()):
            self._add(d)

    def evict_oldest(self, n: int) -> int:
        """Drop up to ``n`` oldest digests (the router's fleet-wide
        byte-ceiling eviction hook — same FIFO order as the
        ``max_paths`` bound); returns how many were dropped."""
        dropped = 0
        while self._order and dropped < n:
            self._digests.discard(self._order.popleft())
            dropped += 1
        if dropped:
            # Set/deque tables never shrink in place, so the sizeof-based
            # footprint would floor at the high-water mark and the byte
            # ceiling could become unreachable; rebuild at current size.
            self._digests = set(self._digests)
            self._order = collections.deque(self._order)
        return dropped

    def match_tokens(self, tokens: Sequence[int]) -> int:
        """Tokens of the longest contiguous cached prefix of
        ``tokens`` this shadow knows about (0 without a block size)."""
        if self.block_size < 1:
            return 0
        depth = 0
        for d in chunk_path_digests(tokens, self.block_size):
            if d not in self._digests:
                break
            depth += 1
        return depth * self.block_size

    def __len__(self) -> int:
        return len(self._digests)

    def approx_footprint_bytes(self) -> int:
        """Shallow host-bytes estimate (the same leak-trend-line role
        as the radix index's ``approx_footprint_bytes``)."""
        total = sys.getsizeof(self._digests) + sys.getsizeof(self._order)
        for d in self._digests:
            total += 2 * sys.getsizeof(d)       # set entry + deque entry
        return total


# ---------------------------------------------------------------------------
# Routing policies (the SchedulerPolicy seam shape, one layer up).
# ---------------------------------------------------------------------------


class RoutingPolicy:
    """Per-request replica choice.

    ``choose(candidates, req, ctx)`` picks one name from the non-empty
    ``candidates`` list (healthy replicas, router order) and returns
    ``(name, info)`` where ``info`` may carry ``affinity_hit_tokens``
    and ``fallback`` for the router's metrics.  ``ctx`` is a read-only
    :class:`RoutingContext`; policies never mutate router state."""

    name = "base"

    def choose(self, candidates: Sequence[str], req: Request,
               ctx: "RoutingContext") -> tuple[str, dict]:
        raise NotImplementedError


class RoutingContext:
    """What a policy may look at: per-replica ``views`` (the poller's
    last probe dicts), ``shadows`` (per-replica
    :class:`ShadowPrefixIndex`), and ``inflight`` (requests routed but
    not yet terminal, per replica — live, not poll-delayed)."""

    def __init__(self, views: dict, shadows: dict, inflight: dict,
                 imbalance: float):
        self.views = views
        self.shadows = shadows
        self.inflight = inflight
        self.imbalance = imbalance

    def load(self, name: str) -> tuple:
        """Sort key: emptier and healthier first, stable by name."""
        v = self.views.get(name, {})
        return (self.inflight.get(name, 0),
                -v.get("goodput", 1.0), name)


class RoundRobinPolicy(RoutingPolicy):
    """Cycle the healthy set in order — the baseline every affinity
    claim is measured against."""

    name = "round_robin"

    def __init__(self) -> None:
        self._next = 0

    def choose(self, candidates: Sequence[str], req: Request,
               ctx: RoutingContext) -> tuple[str, dict]:
        name = candidates[self._next % len(candidates)]
        self._next += 1
        return name, {}


class LeastLoadedPolicy(RoutingPolicy):
    """Fewest in-flight requests wins; goodput breaks ties (a replica
    missing its SLOs is effectively fuller than its queue says)."""

    name = "least_loaded"

    def choose(self, candidates: Sequence[str], req: Request,
               ctx: RoutingContext) -> tuple[str, dict]:
        return min(candidates, key=ctx.load), {}


class PrefixAffinityPolicy(RoutingPolicy):
    """Longest shared cached prefix wins (RadixAttention locality,
    router-side): route to the replica whose shadow index matches the
    most prompt tokens, so the engine's longest-prefix admission skips
    the most prefill.  Ties — including the no-match cold start — fall
    to least-loaded.  When the affinity choice is already
    ``imbalance`` in-flight requests deeper than the emptiest healthy
    replica, locality loses to load and the router falls back to
    least-loaded (``info["fallback"]``), keeping one hot prefix from
    starving the fleet."""

    name = "prefix_affinity"

    def choose(self, candidates: Sequence[str], req: Request,
               ctx: RoutingContext) -> tuple[str, dict]:
        matches = {n: ctx.shadows[n].match_tokens(req.prompt)
                   for n in candidates if n in ctx.shadows}
        best = max(matches.values(), default=0)
        if best <= 0:
            return min(candidates, key=ctx.load), {
                "affinity_hit_tokens": 0, "fallback": False}
        pick = min((n for n in candidates if matches.get(n, 0) == best),
                   key=ctx.load)
        emptiest = min(candidates, key=ctx.load)
        gap = (ctx.inflight.get(pick, 0)
               - ctx.inflight.get(emptiest, 0))
        if gap > ctx.imbalance:
            return emptiest, {
                "affinity_hit_tokens": matches.get(emptiest, 0),
                "fallback": True}
        return pick, {"affinity_hit_tokens": best, "fallback": False}


ROUTING_POLICIES: dict[str, type[RoutingPolicy]] = {
    "round_robin": RoundRobinPolicy,
    "least_loaded": LeastLoadedPolicy,
    "prefix_affinity": PrefixAffinityPolicy,
}


def resolve_routing_policy(
    policy: "RoutingPolicy | str | None" = None,
) -> RoutingPolicy:
    """An instance passes through; a name constructs; ``None`` reads
    ``HVD_TPU_ROUTER_POLICY`` (unset/empty → ``prefix_affinity``)."""
    if isinstance(policy, RoutingPolicy):
        return policy
    name = (policy or os.environ.get("HVD_TPU_ROUTER_POLICY", "")
            or "prefix_affinity")
    cls = ROUTING_POLICIES.get(name)
    if cls is None:
        raise ValueError(
            f"unknown routing policy {name!r}; choose from "
            f"{sorted(ROUTING_POLICIES)}")
    return cls()


# ---------------------------------------------------------------------------
# Replica handles: how the router talks to a backend.
# ---------------------------------------------------------------------------


#: A submission's completion callback.  Called exactly once per
#: submission with the terminal :class:`RequestResult`, or ``None``
#: when the replica died first — ``None`` is the router's failover
#: signal, never a client-visible outcome.
DoneCallback = Callable[["RequestResult | None"], None]


class ReplicaHandle:
    """One backend the router can route to.  Implementations must make
    ``submit`` safe from any thread and guarantee the callback fires
    exactly once (result or ``None``-on-death) for every accepted
    submission."""

    name = "replica"
    block_size = 0      # 0 = unknown / no prefix cache
    #: Whether a dead replica may rejoin routing when probes turn
    #: healthy again.  False for in-process replicas (a dead pump
    #: thread never comes back); True for HTTP replicas (the remote
    #: process can restart, or the probe failure was transient).
    can_revive = False

    def submit(self, req: Request, done_cb: DoneCallback) -> None:
        raise NotImplementedError

    def probe(self) -> dict:
        """Poller view: ``healthy``, ``inflight``, ``queue_depth``,
        ``goodput``, ``free_kv_frac``, ``tp_size`` (chips behind this
        replica — capacity accounting for multi-chip replicas; its
        ``free_kv_frac`` is a fraction of an N-chip logical pool), and
        optionally ``prefix`` (a ``key_digest()`` summary)."""
        raise NotImplementedError

    def stop(self) -> None:
        pass


class LocalReplica(ReplicaHandle):
    """An in-process :class:`~horovod_tpu.serving_scheduler.ServeEngine`
    behind the handle interface, driven by one daemon **pump** thread
    that owns the engine exclusively: submissions from router handler
    threads land in an inbox; the pump drains it into
    ``engine.submit`` and calls ``engine.step`` while work is pending,
    dispatching completion callbacks as requests retire.

    The pump checks the ``serve.router`` fault site (key = replica
    name) before every engine step; a firing rule — transient or
    permanent, the site models process loss either way — kills the
    replica: the pump marks it dead, notifies the router, and fires
    every in-flight callback with ``None`` so the router re-enqueues
    those requests on survivors.  Because replay from the full prompt
    is bit-identical (greedy determinism), the death point never shows
    in any output."""

    _GUARDED_BY_LOCK = ("_inbox", "_cbs", "_dead", "_view", "_stop")

    # Which thread runs what (linted by hvdlint HVD009): the one pump
    # daemon owns the engine; everything else — router handler
    # threads, the poller's probes, supervisor stop — calls in through
    # the public surface and touches shared state only under _lock.
    _THREAD_ROLES = {
        "pump": ["_pump"],
        "callers": ["submit", "probe", "stop"],
    }

    def __init__(self, engine: Any, name: str = "local",
                 faults: "faults_mod.FaultRegistry | None" = None,
                 on_death: "Callable[[LocalReplica], None] | None" = None):
        self.engine = engine
        self.name = name
        self.block_size = (engine.block_size
                           if getattr(engine, "prefix", None) is not None
                           else 0)
        self.faults = faults if faults is not None \
            else faults_mod.FaultRegistry()
        self.on_death = on_death
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._inbox: list[tuple[Request, DoneCallback]] = []
        self._cbs: dict[int, DoneCallback] = {}
        self._dead = False
        self._stop = False
        self._view: dict = {"healthy": True, "inflight": 0,
                            "queue_depth": 0, "goodput": 1.0,
                            "free_kv_frac": 1.0,
                            "tp_size": getattr(engine, "tp_size", 1),
                            "prefix": None}
        self._thread = threading.Thread(
            target=self._pump, name=f"hvd-replica-{name}", daemon=True)
        self._thread.start()

    # -- handle interface --------------------------------------------------

    def submit(self, req: Request, done_cb: DoneCallback) -> None:
        with self._lock:
            if not self._dead and not self._stop:
                self._inbox.append((req, done_cb))
                self._wake.set()
                return
        done_cb(None)       # dead on arrival: immediate failover signal

    def probe(self) -> dict:
        with self._lock:
            return dict(self._view)

    def stop(self) -> None:
        with self._lock:
            self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    # -- the pump thread ---------------------------------------------------

    def _refresh_view_locked(self) -> None:
        eng = self.engine
        total = max(eng.pcache.k.shape[1] - 1, 1)
        free = eng.free_block_count() + eng.cached_block_count()
        self._view = {
            "healthy": not self._dead,
            "inflight": len(self._cbs),
            "queue_depth": len(self._cbs),
            "goodput": eng.slo.goodput(),
            "free_kv_frac": free / total,
            "tp_size": getattr(eng, "tp_size", 1),
            "prefix": (eng.prefix.key_digest()
                       if eng.prefix is not None else None),
        }

    def _pump(self) -> None:
        eng = self.engine
        while True:
            with self._lock:
                if self._stop:
                    return
                batch, self._inbox = self._inbox, []
            for k, (req, cb) in enumerate(batch):
                try:
                    rid = eng.submit(req)
                except (TypeError, ValueError) as e:
                    # Engine-side validation, including TypeError from
                    # lifecycle-field arithmetic on a malformed request:
                    # surface as a terminal REJECTED rather than killing
                    # a well-behaved fleet over one bad request.
                    cb(RequestResult([], REJECTED, e))
                    continue
                except BaseException:
                    for _req3, cb3 in batch[k:]:
                        cb3(None)
                    self._die()
                    return
                if rid in eng.results:      # rejected-on-submit
                    cb(eng.results[rid])
                else:
                    with self._lock:
                        self._cbs[rid] = cb
            stepped = False
            finished: dict[int, RequestResult] = {}
            try:
                if eng.pending():
                    self.faults.check("serve.router", key=self.name)
                    finished = eng.step()
                    stepped = True
            except BaseException:
                self._die()
                return
            for rid, res in finished.items():
                with self._lock:
                    cb2 = self._cbs.pop(rid, None)
                if cb2 is not None:
                    cb2(res)
            try:
                with self._lock:
                    self._refresh_view_locked()
            except BaseException:
                self._die()
                return
            if not stepped:
                self._wake.wait(0.005)
                self._wake.clear()

    def _die(self) -> None:
        """Mark dead, then hand every in-flight request back to the
        router (callbacks fire OUTSIDE the replica lock: they re-enter
        the router, which may call ``submit`` on other replicas)."""
        with self._lock:
            self._dead = True
            self._view = dict(self._view, healthy=False, goodput=0.0)
            orphans = list(self._cbs.values())
            self._cbs.clear()
            pending = list(self._inbox)
            self._inbox.clear()
        if self.on_death is not None:
            self.on_death(self)
        for cb in orphans:
            cb(None)
        for _req, cb in pending:
            cb(None)


class HttpReplica(ReplicaHandle):
    """A backend reached over HTTP: submissions POST to a remote
    ``/v1/generate`` door (typically a single-replica
    :class:`RouterServer` co-located with the engine), health and
    digests come from its monitor's ``/snapshot`` + ``/healthz``.
    Each submission runs in a short-lived daemon thread so the router
    never blocks on the network; a connection error or non-2xx reply
    fires the callback with ``None`` — the same failover signal a
    local pump death produces.  A socket *timeout* is different: the
    backend may be slow but alive and still decoding, so replaying
    the request elsewhere would silently duplicate the work — it
    terminates the request ``FAILED`` instead (and the per-request
    wire budget stretches past ``deadline_s`` when one is set, so an
    engine-side ``TIMEOUT`` always beats the socket to it)."""

    can_revive = True

    def __init__(self, name: str, generate_url: str,
                 monitor_url: str | None = None,
                 block_size: int = 0, timeout_s: float = 30.0):
        self.name = name
        self.generate_url = generate_url.rstrip("/")
        self.monitor_url = (monitor_url.rstrip("/")
                            if monitor_url else None)
        self.block_size = block_size
        self.timeout_s = timeout_s

    def _request_timeout_s(self, req: Request) -> float:
        """Wire budget for one submission: a deadline-carrying request
        gets its own deadline plus the configured margin, so the
        backend's deadline-expiry reply (``TIMEOUT``, tokens-so-far)
        always arrives before the socket gives up."""
        if req.deadline_s is None:
            return self.timeout_s
        return max(self.timeout_s, req.deadline_s + self.timeout_s)

    def submit(self, req: Request, done_cb: DoneCallback) -> None:
        payload = request_to_json(req)
        timeout_s = self._request_timeout_s(req)

        def _post() -> None:
            import socket
            import urllib.error
            import urllib.request
            try:
                http_req = urllib.request.Request(
                    self.generate_url + "/v1/generate",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        http_req, timeout=timeout_s) as resp:
                    body = json.loads(resp.read().decode())
                res = RequestResult(body.get("tokens", []),
                                    body.get("status", FAILED))
                tr = body.get("trace")
                if isinstance(tr, dict):
                    # Remote trace dict (remote clock domain): pass it
                    # through so attribution still sees the *_s spans.
                    res.trace = tr
                done_cb(res)
            except (TimeoutError, socket.timeout) as e:
                # Slow-but-alive backend: fail, don't duplicate.
                done_cb(RequestResult([], FAILED, e))
            except urllib.error.URLError as e:
                if isinstance(getattr(e, "reason", None),
                              (TimeoutError, socket.timeout)):
                    done_cb(RequestResult([], FAILED, e))
                else:
                    done_cb(None)   # refused / reset / non-2xx: failover
            except Exception:
                done_cb(None)

        threading.Thread(target=_post, daemon=True,
                         name=f"hvd-router-post-{self.name}").start()

    def _get_json(self, url: str) -> tuple[int, dict]:
        import urllib.request
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode())

    def probe(self) -> dict:
        view: dict[str, Any] = {"healthy": False, "inflight": 0,
                                "queue_depth": 0, "goodput": 1.0,
                                "free_kv_frac": 1.0, "tp_size": 1,
                                "prefix": None}
        if self.monitor_url is None:
            view["healthy"] = True      # no monitor: assume alive
            return view
        try:
            code, _ = self._get_json(self.monitor_url + "/healthz")
            view["healthy"] = code == 200
            _, snap = self._get_json(self.monitor_url + "/snapshot")
        except Exception:
            return view
        g = snap.get("gauges", {})
        view["queue_depth"] = int(g.get("serve.queue_depth", 0))
        view["inflight"] = int(g.get("serve.queue_depth", 0)
                               + g.get("serve.decoding", 0)
                               + g.get("serve.prefilling", 0))
        view["goodput"] = snap.get("slo", {}).get("goodput", 1.0)
        total = (g.get("kv.free_blocks", 0)
                 + g.get("kv.referenced_blocks", 0)
                 + g.get("kv.cached_blocks", 0))
        if total > 0:
            view["free_kv_frac"] = (g.get("kv.free_blocks", 0)
                                    + g.get("kv.cached_blocks", 0)) / total
        view["tp_size"] = int(g.get("tp.size", 1)) or 1
        view["prefix"] = snap.get("prefix")
        return view


def request_to_json(req: Request) -> dict:
    """The ``POST /v1/generate`` wire form of a :class:`Request`
    (greedy serving fields only — the router is greedy-only, like
    :class:`ServeEngine`)."""
    out = {"prompt": list(req.prompt),
           "max_new_tokens": req.max_new_tokens,
           "eos_id": req.eos_id,
           "deadline_s": req.deadline_s,
           "max_queue_steps": req.max_queue_steps,
           "slo_s": req.slo_s,
           "priority": req.priority}
    ctx = getattr(req, "trace_ctx", None)
    if ctx is not None:
        # Optional causal-trace context: HttpReplica serializes the
        # request at submit time, AFTER the router stamped the current
        # attempt's span — so the remote hop parents under this hop.
        out["trace"] = ctx.to_dict()
    return out


def _opt_number(payload: dict, field: str) -> "float | None":
    v = payload.get(field)
    if v is not None and (isinstance(v, bool)
                          or not isinstance(v, (int, float))):
        raise ValueError(f"{field} must be a number or null")
    return v


def _opt_int(payload: dict, field: str) -> "int | None":
    v = payload.get(field)
    if v is not None and (isinstance(v, bool) or not isinstance(v, int)):
        raise ValueError(f"{field} must be an int or null")
    return v


def request_from_json(payload: dict) -> Request:
    """Parse the wire form back; raises ``ValueError`` on junk (the
    handler maps that to HTTP 400).  EVERY field is type-checked here
    — the lifecycle fields too, not just prompt/budget: an unchecked
    string ``deadline_s`` would only explode later, inside
    ``ServeEngine.submit``/``step`` arithmetic on a pump thread, where
    the router reads the crash as a replica death and replays the same
    poisoned request onto each survivor in turn."""
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    prompt = payload.get("prompt")
    if not isinstance(prompt, list) or \
            not all(isinstance(t, int) for t in prompt):
        raise ValueError("prompt must be a list of token ids")
    mnt = payload.get("max_new_tokens")
    if not isinstance(mnt, int):
        raise ValueError("max_new_tokens must be an int")
    return Request(prompt=prompt, max_new_tokens=mnt,
                   eos_id=_opt_int(payload, "eos_id"),
                   deadline_s=_opt_number(payload, "deadline_s"),
                   max_queue_steps=_opt_int(payload, "max_queue_steps"),
                   slo_s=_opt_number(payload, "slo_s"),
                   priority=_opt_int(payload, "priority") or 0,
                   # Malformed trace dicts degrade to None (untraced),
                   # never 400 — tracing must not fail a request.
                   trace_ctx=tracing_mod.TraceContext.from_dict(
                       payload.get("trace")))


# ---------------------------------------------------------------------------
# Crash-durable request journal (the WAL a restarted router recovers from).
# ---------------------------------------------------------------------------


def load_journal(path: str) -> "tuple[list[dict], dict[str, dict]]":
    """Parse a request-journal WAL into recovery state: a list of
    *incomplete* accept records (accepted, no terminal — these must be
    replayed) and the terminal records of every keyed request (the
    idempotency dedup map).

    The file is plain :class:`~horovod_tpu.metrics.EventLog` JSONL, so
    the torn-line-tolerant ``EventLog.read`` does the parsing: a crash
    mid-append costs at most the half-written last line, never the
    records before it.  Accept/terminal pairs match on the
    ``(pid, rid)`` the EventLog stamps automatically — rids restart at
    0 in every router incarnation, and the pid disambiguates
    incarnations sharing one journal file.  A ``router.replayed``
    marker retires an accept the same way a terminal does: the
    replaying incarnation routed the request under its own fresh
    accept record, so the original must not replay again on the
    restart after next.  A key replayed across several crashes may
    leave several incomplete accepts; one replay suffices, and a key
    that ever reached a terminal needs none."""
    if not path or not os.path.exists(path):
        return [], {}
    accepts: dict[tuple, dict] = {}
    results: dict[str, dict] = {}
    for rec in metrics_mod.EventLog.read(path):
        ident = (rec.get("pid"), rec.get("rid"))
        kind = rec.get("kind")
        if kind == "router.accept":
            accepts[ident] = rec
        elif kind == "router.terminal":
            accepts.pop(ident, None)
            if rec.get("key") is not None:
                # Pop-then-insert so dict order is latest-terminal
                # order — the router's LRU bound keeps the NEWEST
                # keys, so a re-terminated key must move to the back.
                results.pop(rec["key"], None)
                results[rec["key"]] = rec
        elif kind == "router.replayed":
            accepts.pop(ident, None)
    incomplete: list[dict] = []
    seen_keys: set[str] = set()
    for rec in accepts.values():
        key = rec.get("key")
        if key is not None:
            if key in results or key in seen_keys:
                continue
            seen_keys.add(key)
        incomplete.append(rec)
    return incomplete, results


def compact_journal(path: str, keep: "Sequence[dict]") -> None:
    """Rewrite the WAL to just ``keep`` (the records recovery still
    needs: unpaired accepts and the keyed terminals that seed the
    dedup map).  Without this every restart would re-read — and the
    file would forever carry — each paired accept/terminal of every
    request ever served.  Records are written back verbatim (their
    original ``pid``/``rid``/``ts`` intact, so cross-incarnation
    pairing still works) via a temp file + ``os.replace``: a crash
    mid-compaction leaves either the old journal or the new one,
    never a half-written file."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for rec in keep:
            f.write(json.dumps(rec) + "\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# The router itself.
# ---------------------------------------------------------------------------


class _Ticket:
    """One routed request's lifecycle inside the router: which replica
    holds it, whether it was shed, and its terminal result.  All fields
    are mutated under the owning router's lock; ``done`` is the only
    cross-thread wait point.

    The ``*_ts`` / ``*_s`` span fields are the router-side half of
    end-to-end latency attribution (:meth:`RouterServer.request_trace`):
    receive → admission → route decision → journal append → submit,
    all on the owning router's clock (``time.monotonic`` by default)
    so they join the engine :class:`~horovod_tpu.metrics.Trace` stamps
    exactly (same process, same clock)."""

    __slots__ = ("rid", "req", "replica", "shed", "failovers",
                 "result", "done", "done_ts", "policy", "key",
                 "journaled", "recv_ts", "submit_ts", "admission_s",
                 "route_decision_s", "journal_s", "tctx", "tparent",
                 "attempt_ctx", "attempt_parent", "attempt_t0")

    def __init__(self, rid: int, req: Request,
                 now: "float | None" = None):
        self.rid = rid
        self.req = req
        self.replica: str | None = None
        self.shed: str | None = None        # shed reason, when shed
        self.failovers = 0
        self.result: RequestResult | None = None
        self.done = threading.Event()
        self.done_ts = 0.0                  # router clock, for TTL reaping
        self.policy = ""
        self.key: str | None = None         # idempotency key, if any
        self.journaled = False              # has an accept WAL record
        self.recv_ts = (time.monotonic()    # front-door arrival
                        if now is None else now)
        self.submit_ts = 0.0                # first replica submit
        self.admission_s = 0.0              # admission-control check
        self.route_decision_s = 0.0         # policy choose + booking
        self.journal_s = 0.0                # accept WAL append
        # Causal-trace state (None/unsampled on most tickets): the
        # router.request span context, its propagated parent span id,
        # and the CURRENT delivery attempt's span — each failover
        # replay becomes a child of the attempt it replaced, so a
        # multi-hop request renders as one chain in one tree.
        self.tctx: "tracing_mod.TraceContext | None" = None
        self.tparent: str | None = None
        self.attempt_ctx: "tracing_mod.TraceContext | None" = None
        self.attempt_parent: str | None = None
        self.attempt_t0 = 0.0


class _RouterHandler(BaseHTTPRequestHandler):
    """Routes one front-door HTTP request (the monitor ``_Handler``
    pattern: short, lock-free, every touched surface thread-safe)."""

    server: "RouterServer._Server"  # type: ignore[assignment]

    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        router = self.server.router
        router._scrapes.inc()
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, router.metrics.to_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/replicas":
                self._reply(200, json.dumps(router.replicas_report()),
                            "application/json")
            elif path == "/snapshot":
                snap = router.metrics.snapshot()
                snap["replicas"] = router.replicas_report()
                if router.sampler is not None:
                    snap["timeseries"] = router.sampler.report(
                        points=16)
                if router.alerts is not None:
                    snap["alerts"] = router.alerts.report()
                self._reply(200, json.dumps(snap), "application/json")
            elif path == "/healthz":
                code, body = router.health()
                self._reply(code, json.dumps(body), "application/json")
            elif path == "/state":
                self._reply(200, router.state_dump(), "text/plain")
            elif path == "/timeseries":
                if router.sampler is None:
                    self._reply(404, "no sampler attached; set "
                                     "HVD_TPU_SAMPLE_S or pass "
                                     "sampler=...\n", "text/plain")
                else:
                    self._reply(200,
                                json.dumps(router.sampler.report()),
                                "application/json")
            elif path == "/alerts":
                if router.alerts is None:
                    self._reply(404, "no alert manager attached "
                                     "(HVD_TPU_ALERTS)\n",
                                "text/plain")
                else:
                    self._reply(200,
                                json.dumps(router.alerts.report()),
                                "application/json")
            elif path == "/advice":
                if router.advisor is None:
                    self._reply(404, "no capacity advisor attached\n",
                                "text/plain")
                else:
                    router.advisor.recommend()
                    self._reply(200,
                                json.dumps(router.advisor.report()),
                                "application/json")
            elif path == "/autoscaler":
                if router.autoscaler is None:
                    self._reply(404, "no autoscaler attached "
                                     "(HVD_TPU_AUTOSCALE)\n",
                                "text/plain")
                else:
                    self._reply(200,
                                json.dumps(router.autoscaler.report()),
                                "application/json")
            elif path == "/device":
                rep = router.device_report()
                if not rep["replicas"]:
                    self._reply(404, "no replica exposes device "
                                     "telemetry; construct engines "
                                     "with device_telemetry=True or "
                                     "set HVD_TPU_DEVICE_TELEMETRY=1"
                                     "\n", "text/plain")
                else:
                    self._reply(200, json.dumps(rep),
                                "application/json")
            elif path == "/traces":
                self._reply(200, json.dumps(router.tracer.recent()),
                            "application/json")
            else:
                self._reply(404, "unknown path; try /v1/generate "
                                 "/replicas /snapshot /healthz "
                                 "/metrics /state /device "
                                 "/timeseries /alerts /advice "
                                 "/autoscaler /traces\n",
                            "text/plain")
        except BrokenPipeError:
            pass

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        router = self.server.router
        path = self.path.split("?", 1)[0]
        try:
            if path != "/v1/generate":
                self._reply(404, "unknown path; POST /v1/generate\n",
                            "text/plain")
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(n).decode())
                req = request_from_json(payload)
                if req.trace_ctx is None:
                    # W3C traceparent-style header — the JSON "trace"
                    # field wins when both arrive (same trust domain,
                    # and HttpReplica hops only send the field).
                    req.trace_ctx = tracing_mod.TraceContext.from_header(
                        self.headers.get("traceparent"))
                key = payload.get("idempotency_key")
                if key is not None and not isinstance(key, str):
                    raise ValueError(
                        "idempotency_key must be a string or null")
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, json.dumps({"error": str(e)}),
                            "application/json")
                return
            code, body = router.handle_generate(req, key)
            self._reply(code, json.dumps(body), "application/json")
        except BrokenPipeError:
            pass

    def log_message(self, fmt: str, *args: Any) -> None:
        pass        # requests must not spam the job's stderr


class RouterServer:
    """The fleet front door: routes, sheds, fails over, and reports.

    ``replicas`` is a list of :class:`ReplicaHandle`; in-process
    engines wrap in :class:`LocalReplica` automatically when you pass
    bare engines.  The HTTP server binds at construction (``port=0``
    picks an ephemeral port — read ``.port``) and serves after
    :meth:`start`; the programmatic surface (:meth:`route` /
    :meth:`result`) works without ever starting HTTP, which is how the
    bench arm and most tests drive it.

    Thread model: handler threads call :meth:`route`/:meth:`result`,
    replica pump/POST threads call the completion callbacks, one
    poller thread refreshes views — all cross-thread state lives
    behind ``_lock`` (see ``_GUARDED_BY_LOCK``).  Lock order is
    router → replica; replica callbacks always fire with no replica
    lock held, so the reverse edge never forms."""

    _GUARDED_BY_LOCK = ("_tickets", "_views", "_shadows", "_inflight",
                        "_routed", "_dead", "_cordoned", "_probe_fails",
                        "_next_rid", "_journal_results",
                        "_journal_inflight", "_journal_waiters")

    # Which thread runs what (linted by hvdlint HVD009).  The poller
    # entries include the membership mutators because supervisor/
    # autoscaler call replace/add/retire/cordon from inside poll_now's
    # tick; "lifecycle" is the owning (main/test) thread, which also
    # drives membership during setup and drain.
    _THREAD_ROLES = {
        "http": ["handle_generate", "route", "result", "request_trace",
                 "health", "state_dump", "replicas_report",
                 "memory_report", "cordoned"],
        "poller": ["_poll_loop", "poll_now", "reap_tickets",
                   "_shadow_bytes", "_enforce_shadow_bound",
                   "replace_replica", "add_replica",
                   "retire_replica", "cordon_replica",
                   "uncordon_replica"],
        "replica-callback": ["_on_done", "_on_replica_death",
                             "_emit_ticket_spans"],
        "lifecycle": ["start", "stop", "replay_journal",
                      "add_replica", "retire_replica"],
    }

    class _Server(ThreadingHTTPServer):
        daemon_threads = True
        router: "RouterServer"

    def __init__(self, replicas: Sequence[Any], *,
                 policy: "RoutingPolicy | str | None" = None,
                 registry: "metrics_mod.MetricsRegistry | None" = None,
                 faults: "faults_mod.FaultRegistry | None" = None,
                 port: int = 0, host: str = "127.0.0.1",
                 min_goodput: float | None = None,
                 min_free_kv: float | None = None,
                 imbalance: float | None = None,
                 poll_s: float | None = None,
                 max_failovers: int | None = None,
                 probe_fails: int | None = None,
                 ticket_ttl_s: float | None = None,
                 shadow_max_paths: int = 4096,
                 shadow_max_bytes: int | None = None,
                 journal: str | None = None,
                 journal_keys: int | None = None,
                 drain_s: float | None = None,
                 sampler: "Any | bool | None" = None,
                 alerts: "Any | bool | None" = None,
                 clock: Callable[[], float] = time.monotonic):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas: list[ReplicaHandle] = []
        names = set()
        for i, r in enumerate(replicas):
            if not isinstance(r, ReplicaHandle):
                r = LocalReplica(r, name=f"replica{i}", faults=faults)
            if r.name in names:
                raise ValueError(f"duplicate replica name {r.name!r}")
            names.add(r.name)
            if isinstance(r, LocalReplica) and r.on_death is None:
                r.on_death = self._on_replica_death
            self.replicas.append(r)
        self.policy = resolve_routing_policy(policy)
        self.metrics = (registry if registry is not None
                        else metrics_mod.MetricsRegistry())
        self.min_goodput = (min_goodput if min_goodput is not None else
                            env_float("HVD_TPU_ROUTER_MIN_GOODPUT", 0.0))
        self.min_free_kv = (min_free_kv if min_free_kv is not None else
                            env_float("HVD_TPU_ROUTER_MIN_FREE_KV", 0.0))
        self.imbalance = (imbalance if imbalance is not None else
                          env_float("HVD_TPU_ROUTER_IMBALANCE", 4.0))
        self.poll_s = (poll_s if poll_s is not None else
                       env_float("HVD_TPU_ROUTER_POLL_S", 0.05))
        # Replays allowed per request before it fails terminally — the
        # backstop that keeps a poison request (one that kills every
        # pump it touches) from cascading through the whole fleet.
        self.max_failovers = int(
            max_failovers if max_failovers is not None else
            env_float("HVD_TPU_ROUTER_MAX_FAILOVERS", 3))
        # Consecutive failed probes before a revivable (HTTP) replica
        # is marked dead; one blip or a still-starting backend must not
        # permanently shrink the fleet.
        self.probe_fails = max(1, int(
            probe_fails if probe_fails is not None else
            env_float("HVD_TPU_ROUTER_PROBE_FAILS", 3)))
        self.ticket_ttl_s = (
            ticket_ttl_s if ticket_ttl_s is not None else
            env_float("HVD_TPU_ROUTER_TICKET_TTL_S", 600.0))
        self.drain_s = (drain_s if drain_s is not None else
                        env_float("HVD_TPU_ROUTER_DRAIN_S", 5.0))
        self.faults = (faults if faults is not None
                       else faults_mod.FaultRegistry())
        #: Every router timestamp — ticket stamps, reap TTLs, drain
        #: deadlines, e2e spans — reads this clock, so a virtual clock
        #: (the simfleet driver) advances the whole bookkeeping plane
        #: without sleeping.  Default is the wall ``time.monotonic``;
        #: real waits (stop's drain sleep, the poller's cadence) stay
        #: on wall time regardless.
        self.clock = clock
        # Causal tracing plane: spans persist through this registry's
        # event sink; the sampler decision is pure (seed, rid) — see
        # horovod_tpu.tracing.  Fraction 0 (the default) costs one
        # attribute test per request.
        self.tracer = tracing_mod.Tracer(self.metrics)
        self._trace_fraction = tracing_mod.env_sample_fraction()
        self._trace_seed = tracing_mod.env_trace_seed()

        self._lock = threading.Lock()
        self._next_rid = 0
        self._tickets: dict[int, _Ticket] = {}
        self.shadow_max_paths = shadow_max_paths
        # Fleet-wide shadow-index byte ceiling: the per-replica
        # max_paths bound caps each index, but at hundreds of replicas
        # the UNION is the leak — past the ceiling the poller evicts
        # oldest digests from the fattest indexes (<= 0 = unbounded).
        self.shadow_max_bytes = int(
            shadow_max_bytes if shadow_max_bytes is not None else
            env_float("HVD_TPU_ROUTER_SHADOW_MAX_MB", 64.0)
            * 1024 * 1024)
        self._probe_fails: dict[str, int] = {r.name: 0
                                             for r in self.replicas}
        self._views: dict[str, dict] = {}
        self._shadows: dict[str, ShadowPrefixIndex] = {
            r.name: ShadowPrefixIndex(r.block_size, shadow_max_paths)
            for r in self.replicas}
        self._inflight: dict[str, int] = {r.name: 0
                                          for r in self.replicas}
        self._routed: dict[str, int] = {r.name: 0 for r in self.replicas}
        self._dead: set[str] = set()
        # Cordoned replicas stay healthy and keep draining their
        # in-flight work but receive no new placements — the
        # autoscaler's scale-down staging area.
        self._cordoned: set[str] = set()

        # Crash-durable request journal (off unless a path is set).
        # Recovery happens HERE, before any routing: incomplete accepts
        # from a previous incarnation park in _journal_pending until
        # start() (or an explicit replay_journal()) re-submits them, and
        # journaled terminals seed the idempotency dedup map.
        self.journal_path = (journal if journal is not None else
                            os.environ.get("HVD_TPU_ROUTER_JOURNAL", "")) \
            or None
        # Keyed terminal results kept for idempotency dedup, LRU by
        # terminal/dedup-hit time.  Past the bound, exactly-once
        # degrades to at-least-once (an evicted key's duplicate
        # re-runs) — the price of a router whose memory and WAL don't
        # grow with lifetime traffic.
        self.journal_keys = max(1, int(
            journal_keys if journal_keys is not None else
            env_float("HVD_TPU_ROUTER_JOURNAL_KEYS", 4096)))
        self._journal: metrics_mod.EventLog | None = None
        self._journal_results: dict[str, RequestResult] = {}
        self._journal_inflight: dict[str, int] = {}     # key -> live rid
        self._journal_waiters: dict[str, list[_Ticket]] = {}
        self._journal_pending: list[dict] = []          # setup-only
        if self.journal_path:
            pending, terms = load_journal(self.journal_path)
            self._journal_pending = pending
            # File order is terminal order, so the newest keys win the
            # bound; compaction drops everything recovery no longer
            # needs (paired records, evicted keys) from the file too.
            kept = list(terms.items())[-self.journal_keys:]
            for key, rec in kept:
                self._journal_results[key] = RequestResult(
                    rec.get("tokens") or [], rec.get("status", FAILED))
            compact_journal(self.journal_path,
                            pending + [rec for _, rec in kept])
            self._journal = metrics_mod.EventLog(self.journal_path)

        #: A :class:`~horovod_tpu.supervisor.ReplicaSupervisor`, once
        #: attached — ticked by the poller, reported by health().
        self.supervisor: Any = None
        #: Optional ``(replica_name, request)`` observer fired after
        #: each placement, outside the lock — the supervisor's
        #: warm-prompt feed.
        self.on_route: "Callable[[str, Request], None] | None" = None

        # Registered up front (literal names — the HVD005 contract) so
        # router snapshots are schema-stable from request 0; the
        # per-decision bump composes "router.routed." + policy.name.
        self.metrics.counter("router.routed.round_robin")
        self.metrics.counter("router.routed.least_loaded")
        self.metrics.counter("router.routed.prefix_affinity")
        self.metrics.counter("router.requests")
        self.metrics.counter("router.sheds")
        self.metrics.counter("router.failovers")
        self.metrics.counter("router.replica_deaths")
        self.metrics.counter("router.replica_revives")
        self.metrics.counter("router.affinity_fallbacks")
        self.metrics.counter("router.journal_appends")
        self.metrics.counter("router.journal_errors")
        self.metrics.counter("router.journal_replays")
        self.metrics.counter("router.journal_dedups")
        self.metrics.counter("router.shadow_evictions")
        self.metrics.histogram("router.affinity_hit_tokens")
        self.metrics.histogram("router.poll_s")
        self.metrics.histogram("router.route_decision_s")
        self.metrics.histogram("router.admission_s")
        self.metrics.histogram("router.journal_append_s")
        self.metrics.histogram("router.replica_queue_s")
        self.metrics.histogram("router.e2e_s")
        self.metrics.histogram("router.failover_hops")
        self.metrics.gauge("router.replicas_healthy").set(
            len(self.replicas))
        self.metrics.gauge("router.fleet_size").set(len(self.replicas))
        self.metrics.gauge("router.inflight").set(0)
        self.metrics.gauge("router.shadow_index_bytes").set(0)
        # Scrape odometer off the shared generation cell (the monitor
        # trick) so idle /metrics scrapes stay render-cached.
        self._scrapes = self.metrics.counter("monitor.scrapes")
        self._scrapes._gen = metrics_mod._Gen()

        # Health plane over the router's own registry, ticked by the
        # poller (no extra threads): sampler -> alert rules -> capacity
        # advisor.  Same contract as ServeEngine: None = env-driven,
        # False = off, an instance is used as-is.
        from horovod_tpu import alerts as alerts_mod
        from horovod_tpu import timeseries as timeseries_mod
        if sampler is False:
            self.sampler = None
        elif sampler is None:
            self.sampler = timeseries_mod.maybe_sampler(self.metrics)
        else:
            self.sampler = sampler
        if alerts is False or self.sampler is None:
            self.alerts = None
        elif alerts is None:
            self.alerts = alerts_mod.maybe_alerts(
                self.sampler, self.metrics)
        else:
            self.alerts = alerts
        self.advisor = (alerts_mod.CapacityAdvisor(
            self.sampler, alerts=self.alerts, registry=self.metrics)
            if self.sampler is not None else None)
        #: A :class:`~horovod_tpu.autoscaler.FleetAutoscaler`, once
        #: attached — ticked by the poller after the health plane so
        #: it actuates against this pass's fresh views.  Env-gated
        #: here (HVD_TPU_AUTOSCALE); tests and campaigns attach one
        #: explicitly.
        from horovod_tpu import autoscaler as autoscaler_mod
        self.autoscaler: Any = None
        autoscaler_mod.maybe_autoscaler(self)

        self._httpd = RouterServer._Server((host, port), _RouterHandler)
        self._httpd.router = self
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread: threading.Thread | None = None
        self._poll_stop = threading.Event()
        self._poll_thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RouterServer":
        """Serve HTTP and start the replica poller (idempotent)."""
        if self._http_thread is None:
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"hvd-router-:{self.port}", daemon=True)
            self._http_thread.start()
        if self._poll_thread is None:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="hvd-router-poll",
                daemon=True)
            self._poll_thread.start()
        self.replay_journal()
        return self

    def stop(self, stop_replicas: bool = True,
             drain_s: float | None = None) -> None:
        """Drain, then shut down.  The drain phase waits up to
        ``drain_s`` (default ``HVD_TPU_ROUTER_DRAIN_S``) for in-flight
        requests to finish instead of abandoning pump threads with
        work queued; a request still live at the deadline is failed
        terminally — unblocking its waiters — but a journaled one
        skips its terminal WAL record, so a restarted router replays
        it rather than losing it."""
        drain = self.drain_s if drain_s is None else drain_s
        deadline = time.monotonic() + max(drain, 0.0)
        while time.monotonic() < deadline:
            with self._lock:
                busy = sum(self._inflight.values())
            if busy == 0:
                break
            time.sleep(0.005)
        undrained: list[_Ticket] = []
        with self._lock:
            for t in self._tickets.values():
                if t.replica is not None and not t.done.is_set():
                    t.journaled = False     # keep the accept unpaired
                    t.result = RequestResult([], FAILED, RuntimeError(
                        "router shut down before completion"))
                    t.done_ts = self.clock()
                    undrained.append(t)
            # Parked idempotency duplicates have replica=None, so the
            # scan above misses them — and the original they wait on
            # was just failed WITHOUT a _journal_terminal (its accept
            # must stay unpaired for replay), so nothing will ever
            # release them.  Fail them here or their handle_generate
            # threads block forever on done.wait().
            for waiters in self._journal_waiters.values():
                for w in waiters:
                    if not w.done.is_set():
                        w.result = RequestResult([], FAILED, RuntimeError(
                            "router shut down before completion"))
                        w.done_ts = self.clock()
                        undrained.append(w)
            self._journal_waiters.clear()
            self._journal_inflight.clear()
        if undrained:
            self.metrics.event("router.drain_abandoned",
                               count=len(undrained),
                               journaled=self._journal is not None)
        for t in undrained:
            t.done.set()
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None
        if self._http_thread is not None:
            self._httpd.shutdown()
            self._http_thread.join(timeout=5)
            self._http_thread = None
        self._httpd.server_close()
        if stop_replicas:
            for r in self.replicas:
                r.stop()
        if self._journal is not None:
            self._journal.close()

    # -- routing -----------------------------------------------------------

    def route(self, req: Request, *,
              idempotency_key: str | None = None) -> int:
        """Admit-or-shed, choose a replica, submit.  Returns the router
        request id (poll :meth:`result`); a shed request gets a
        terminal ``REJECTED`` result immediately.

        ``idempotency_key`` (journaled routers only) makes the request
        exactly-once across client retries and router restarts: a key
        whose terminal result is journaled answers from the journal
        without touching a replica; a key still in flight shares the
        original's outcome instead of running twice."""
        return self._route(req, idempotency_key).rid

    def _route(self, req: Request,
               idempotency_key: str | None = None) -> _Ticket:
        self.metrics.counter("router.requests").inc()
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            ticket = _Ticket(rid, req, self.clock())
            ticket.key = idempotency_key
            self._tickets[rid] = ticket
            in_ctx = getattr(req, "trace_ctx", None)
            if in_ctx is not None:
                # Propagated context (client header/field, or a journal
                # replay's original span): this hop is its child.
                ticket.tctx = in_ctx.child("router.request")
                ticket.tparent = in_ctx.span_id
            elif self._trace_fraction > 0.0:
                # Router-origin root, head-sampled on the request id —
                # pure (seed, rid), so simfleet replays sample
                # identically.
                ticket.tctx = tracing_mod.TraceContext.root(
                    f"router:{rid}", "router.request",
                    self._trace_fraction, self._trace_seed)
                if ticket.tctx is not None:
                    tracing_mod.count_sampled(self.metrics)
            if self._journal is not None and idempotency_key is not None:
                prior = self._journal_results.pop(idempotency_key, None)
                if prior is not None:
                    # Exactly-once: the journaled terminal IS the
                    # answer; the duplicate never reaches a replica.
                    # Re-insert to refresh LRU recency — a key still
                    # being retried is the last one to evict.
                    self._journal_results[idempotency_key] = prior
                    ticket.result = prior
                    ticket.done_ts = self.clock()
                    self.metrics.counter("router.journal_dedups").inc()
                elif idempotency_key in self._journal_inflight:
                    # Original still running: park on its outcome.
                    self._journal_waiters.setdefault(
                        idempotency_key, []).append(ticket)
                    self.metrics.counter("router.journal_dedups").inc()
                    return ticket
            if ticket.result is None:
                t0 = self.clock()
                shed = self._admission_locked()
                ticket.admission_s = self.clock() - t0
                if shed is not None:
                    self._shed_locked(ticket, shed)
                    return ticket
                if self._journal is not None:
                    ticket.journaled = True
                    if idempotency_key is not None:
                        self._journal_inflight[idempotency_key] = rid
                t0 = self.clock()
                handle, info = self._place_locked(ticket)
                ticket.route_decision_s = self.clock() - t0
        if ticket.result is not None:       # journal dedup hit
            ticket.done.set()
            return ticket
        if ticket.journaled:
            # Accept is durable BEFORE the submit: a crash between the
            # append and the callback replays the request on restart.
            t0 = self.clock()
            self._journal_append(
                "router.accept", rid=rid, key=idempotency_key,
                req=request_to_json(req),
                # The router.request span context rides the accept
                # record so a crash-recovery replay rejoins the SAME
                # trace as a child of this span (one tree across
                # incarnations).
                trace=(ticket.tctx.to_dict()
                       if ticket.tctx is not None else None))
            ticket.journal_s = self.clock() - t0
            self.metrics.histogram("router.journal_append_s").observe(
                ticket.journal_s)
        self.metrics.histogram("router.admission_s").observe(
            ticket.admission_s)
        self.metrics.histogram("router.route_decision_s").observe(
            ticket.route_decision_s)
        self.metrics.event("router.route", rid=rid, replica=handle.name,
                           policy=ticket.policy, **info)
        if self.on_route is not None:
            self.on_route(handle.name, req)
        ticket.submit_ts = self.clock()
        if ticket.tctx is not None:
            # First delivery attempt: the engine (or remote hop) will
            # parent its serve.request span under this attempt, so the
            # request object carries the attempt context from here on.
            ticket.attempt_ctx = ticket.tctx.child("replica.attempt")
            ticket.attempt_parent = ticket.tctx.span_id
            ticket.attempt_t0 = ticket.submit_ts
            req.trace_ctx = ticket.attempt_ctx
        handle.submit(req, lambda res, t=ticket: self._on_done(t, res))
        return ticket

    def result(self, rid: int,
               timeout: float | None = None) -> RequestResult | None:
        """Block for a routed request's terminal result (``None`` on
        timeout — the request is still in flight somewhere)."""
        with self._lock:
            ticket = self._tickets.get(rid)
        if ticket is None:
            raise KeyError(f"unknown router rid {rid}")
        if not ticket.done.wait(timeout):
            return None
        return ticket.result

    def request_trace(self, rid: int) -> "dict | None":
        """The merged end-to-end latency trace for a finished rid:
        the engine-side :class:`~horovod_tpu.metrics.Trace` fields
        (queue wait, TTFT, decode cadence) plus a ``router`` sub-dict
        of front-door spans (receive → admission → route decision →
        journal append → submit → done).  ``None`` while the request
        is still in flight; ``KeyError`` for an unknown/reaped rid —
        read it before the ticket TTL, like :meth:`result`."""
        with self._lock:
            ticket = self._tickets.get(rid)
        if ticket is None:
            raise KeyError(f"unknown router rid {rid}")
        if not ticket.done.is_set():
            return None
        return self._merged_trace(ticket)

    def _merged_trace(self, ticket: _Ticket) -> dict:
        """Join the engine trace with router-side spans.  All stamps
        are ``time.monotonic`` in THIS process, so local-replica engine
        stamps subtract cleanly from router stamps; an HTTP replica's
        trace arrives as a dict in the remote clock domain and is
        passed through untouched (its ``*_s`` durations still join)."""
        base: dict = {}
        res = ticket.result
        tr = getattr(res, "trace", None)
        if hasattr(tr, "to_dict"):
            base = tr.to_dict()
        elif isinstance(tr, dict):
            base = {k: v for k, v in tr.items() if k != "router"}
        router: dict = {
            "recv_ts": ticket.recv_ts,
            "submit_ts": ticket.submit_ts or None,
            "done_ts": ticket.done_ts or None,
            "route_decision_s": ticket.route_decision_s,
            "admission_s": ticket.admission_s,
            "journal_append_s": ticket.journal_s,
            "accept_to_submit_s": (ticket.submit_ts - ticket.recv_ts
                                   if ticket.submit_ts > 0 else None),
            "failovers": ticket.failovers,
            "replica": ticket.replica,
            "shed": ticket.shed,
            # Sampled requests carry their trace identity out to the
            # client (and loadgen's attribution records) so a slow
            # reply links straight to its reconstructable span tree.
            "trace_id": (ticket.tctx.trace_id
                         if ticket.tctx is not None else None),
            "span_id": (ticket.tctx.span_id
                        if ticket.tctx is not None else None),
        }
        if ticket.done_ts > 0:
            router["e2e_s"] = ticket.done_ts - ticket.recv_ts
        enq = getattr(tr, "enqueue_ts", None)
        if ticket.submit_ts > 0 and enq is not None:
            router["replica_queue_s"] = max(enq - ticket.submit_ts, 0.0)
        term = getattr(tr, "terminal_ts", None)
        if term is not None and ticket.done_ts > 0:
            router["finish_s"] = max(ticket.done_ts - term, 0.0)
        base["router"] = router
        return base

    def _emit_ticket_spans(self, ticket: _Ticket, res: Any,
                           attempt_done: bool = False) -> None:
        """Post-hoc span emission for a finished sampled ticket — all
        stamps come from the ticket (the injectable router clock), so
        virtual-time drivers trace without wall reads.  The front-door
        sub-spans (admission → route decision → journal append) tile
        sequentially from the receive stamp; ``attempt_done`` skips the
        final attempt span when the failover path already closed it."""
        tctx = ticket.tctx
        cur = ticket.recv_ts
        for name, dur in (("router.admission", ticket.admission_s),
                          ("router.route_decision",
                           ticket.route_decision_s),
                          ("router.journal_append", ticket.journal_s)):
            if dur > 0.0:
                self.tracer.span(tctx.child(name), name, cur, cur + dur,
                                 parent_id=tctx.span_id)
                cur += dur
        if ticket.attempt_ctx is not None and not attempt_done:
            self.tracer.span(
                ticket.attempt_ctx, "replica.attempt",
                ticket.attempt_t0, ticket.done_ts,
                parent_id=ticket.attempt_parent, rid=ticket.rid,
                replica=ticket.replica,
                status=getattr(res, "status", None))
        self.tracer.span(
            tctx, "router.request", ticket.recv_ts, ticket.done_ts,
            parent_id=ticket.tparent, rid=ticket.rid,
            replica=ticket.replica, failovers=ticket.failovers,
            policy=ticket.policy, shed=ticket.shed,
            status=getattr(res, "status", None))

    def reap_tickets(self, older_than_s: float | None = None) -> int:
        """Drop tickets whose terminal result has been readable for at
        least ``older_than_s`` seconds (default ``ticket_ttl_s``);
        returns how many were dropped.  The poller runs this every
        pass and ``handle_generate`` pops its own ticket with the HTTP
        reply, so the ticket table stays bounded under an indefinite
        request stream.  Programmatic :meth:`route`/:meth:`result`
        users must read a result within the TTL — :meth:`result`
        raises ``KeyError`` for a reaped rid."""
        ttl = self.ticket_ttl_s if older_than_s is None else older_than_s
        now = self.clock()
        with self._lock:
            dead = [rid for rid, t in self._tickets.items()
                    if t.done.is_set() and now - t.done_ts >= ttl]
            for rid in dead:
                del self._tickets[rid]
        return len(dead)

    def handle_generate(self, req: Request,
                        idempotency_key: str | None = None,
                        ) -> tuple[int, dict]:
        """The ``POST /v1/generate`` body: route, wait, and shape the
        JSON reply.  Shed requests answer 429 (back off and retry is
        the right client response to load shedding); every other
        terminal status is a 200 whose ``status`` field speaks."""
        ticket = self._route(req, idempotency_key)
        ticket.done.wait()
        with self._lock:
            # Claim the ticket with the reply: the HTTP reply is its
            # only reader, and a front door that never forgets a
            # finished request leaks prompt+result tokens without
            # bound.  The claim must come AFTER the wait — a ticket
            # popped at entry is invisible to stop()'s undrained scan,
            # which would leave this handler thread blocked forever on
            # a shutdown-abandoned request.
            self._tickets.pop(ticket.rid, None)
        res = ticket.result
        body = {"rid": ticket.rid, "status": res.status,
                "tokens": list(res),
                "replica": ticket.replica,
                "failovers": ticket.failovers,
                "trace": self._merged_trace(ticket)}
        if ticket.shed is not None:
            body["shed"] = ticket.shed
        if res.error is not None:
            body["error"] = str(res.error)
        code = 429 if ticket.shed is not None else 200
        return code, body

    def _admission_locked(self) -> str | None:
        """Shed reason, or ``None`` to admit.  Fleet goodput / free-KV
        are means over the healthy replicas' last-polled views; a
        never-polled replica counts as healthy and empty (no evidence
        of badness — exactly the SLO window's empty-window stance)."""
        healthy = [r.name for r in self.replicas
                   if r.name not in self._dead
                   and r.name not in self._cordoned]
        if not healthy:
            # A fully-cordoned-but-alive fleet still serves (the
            # cordon is advisory scale-down staging, not an outage);
            # only a fleet with no live replica at all sheds.
            healthy = [r.name for r in self.replicas
                       if r.name not in self._dead]
        if not healthy:
            return "no_replicas"
        if self.min_goodput > 0:
            vals = [self._views.get(n, {}).get("goodput", 1.0)
                    for n in healthy]
            if sum(vals) / len(vals) < self.min_goodput:
                return "goodput"
        if self.min_free_kv > 0:
            vals = [self._views.get(n, {}).get("free_kv_frac", 1.0)
                    for n in healthy]
            if sum(vals) / len(vals) < self.min_free_kv:
                return "free_kv"
        return None

    def _shed_locked(self, ticket: _Ticket, reason: str) -> None:
        ticket.shed = reason
        ticket.result = RequestResult([], REJECTED)
        self.metrics.counter("router.sheds").inc()
        self.metrics.event("router.shed", rid=ticket.rid, reason=reason)
        ticket.done_ts = self.clock()
        ticket.done.set()

    def _place_locked(
            self, ticket: _Ticket) -> tuple[ReplicaHandle, dict]:
        """Pick a healthy replica with the policy and book the ticket
        onto it (caller submits outside the lock); returns the handle
        plus the policy's info dict for the ``router.route`` event."""
        candidates = [r.name for r in self.replicas
                      if r.name not in self._dead
                      and r.name not in self._cordoned]
        if not candidates:
            # Never fail a request over a cordon: if every live
            # replica is cordoned (mid-drain fleet at the min bound,
            # or a failover racing a scale-down), place on a live
            # cordoned replica rather than dropping.
            candidates = [r.name for r in self.replicas
                          if r.name not in self._dead]
        ctx = RoutingContext(self._views, self._shadows, self._inflight,
                             self.imbalance)
        name, info = self.policy.choose(candidates, ticket.req, ctx)
        ticket.replica = name
        ticket.policy = self.policy.name
        self._routed[name] = self._routed.get(name, 0) + 1
        self._inflight[name] = self._inflight.get(name, 0) + 1
        self.metrics.counter("router.routed." + self.policy.name).inc()
        self.metrics.gauge("router.inflight").set(
            sum(self._inflight.values()))
        if "affinity_hit_tokens" in info:
            self.metrics.histogram("router.affinity_hit_tokens").observe(
                info["affinity_hit_tokens"])
        if info.get("fallback"):
            self.metrics.counter("router.affinity_fallbacks").inc()
        self._shadows[name].observe(ticket.req.prompt)
        return self._handle(name), info

    def _handle(self, name: str) -> ReplicaHandle:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(name)

    # -- completion + failover ---------------------------------------------

    def _on_done(self, ticket: _Ticket,
                 res: "RequestResult | None") -> None:
        """Completion callback from a replica thread.  A real result is
        terminal; ``None`` means the replica died with this request in
        flight — re-enqueue it on a survivor (replay from the full
        prompt is bit-identical) or fail it when the fleet is gone."""
        if res is not None:
            with self._lock:
                if ticket.done.is_set():
                    return
                ticket.result = res
                if ticket.replica is not None:
                    n = self._inflight.get(ticket.replica, 1)
                    self._inflight[ticket.replica] = max(n - 1, 0)
                self.metrics.gauge("router.inflight").set(
                    sum(self._inflight.values()))
                ticket.done_ts = self.clock()
            self.metrics.histogram("router.e2e_s").observe(
                ticket.done_ts - ticket.recv_ts,
                # OpenMetrics-style exemplar: the p99 bucket links
                # straight to a reconstructable trace.
                exemplar=(ticket.tctx.trace_id
                          if ticket.tctx is not None else None))
            self.metrics.histogram("router.failover_hops").observe(
                float(ticket.failovers))
            tr = getattr(res, "trace", None)
            if (ticket.submit_ts > 0
                    and getattr(tr, "enqueue_ts", None) is not None):
                # Same-process monotonic clocks: the engine enqueue
                # stamp joins the router submit stamp directly.
                self.metrics.histogram("router.replica_queue_s").observe(
                    max(tr.enqueue_ts - ticket.submit_ts, 0.0))
            if ticket.tctx is not None:
                self._emit_ticket_spans(ticket, res)
            ticket.done.set()
            if ticket.journaled:
                self._journal_terminal(ticket, res)
            return
        with self._lock:
            if ticket.done.is_set():
                return
            old = ticket.replica
            if old is not None:
                n = self._inflight.get(old, 1)
                self._inflight[old] = max(n - 1, 0)
            err: RuntimeError | None = None
            if all(r.name in self._dead for r in self.replicas):
                err = RuntimeError("no healthy replicas for failover")
            elif ticket.failovers >= self.max_failovers:
                # A request that kills every replica it lands on would
                # otherwise walk the whole fleet dead; stop replaying
                # after max_failovers and fail THIS request instead.
                err = RuntimeError(
                    f"request failed over {ticket.failovers} times "
                    f"(max_failovers={self.max_failovers}); not "
                    "replaying again")
            if err is not None:
                ticket.result = RequestResult([], FAILED, err)
                self.metrics.gauge("router.inflight").set(
                    sum(self._inflight.values()))
                ticket.done_ts = self.clock()
            else:
                ticket.failovers += 1
                self.metrics.counter("router.failovers").inc()
                handle, info = self._place_locked(ticket)
            failed_attempt = None
            if ticket.tctx is not None and ticket.attempt_ctx is not None:
                # Close the failed attempt's span and (on replay) chain
                # the next attempt as its CHILD — the failover replay
                # renders under the hop it replaced, one tree.
                now = self.clock()
                failed_attempt = (ticket.attempt_ctx,
                                  ticket.attempt_parent,
                                  ticket.attempt_t0, now, old)
                if err is None:
                    ticket.attempt_parent = ticket.attempt_ctx.span_id
                    ticket.attempt_ctx = ticket.attempt_ctx.child(
                        "replica.attempt", seq=ticket.failovers)
                    ticket.attempt_t0 = now
                    ticket.req.trace_ctx = ticket.attempt_ctx
        if failed_attempt is not None:
            ctx, parent, t0, t1, replica = failed_attempt
            self.tracer.span(ctx, "replica.attempt", t0, t1,
                             parent_id=parent, rid=ticket.rid,
                             replica=replica,
                             status="failover" if err is None
                             else "failed")
        if err is not None:
            if ticket.tctx is not None:
                self._emit_ticket_spans(ticket, ticket.result,
                                        attempt_done=failed_attempt
                                        is not None)
            ticket.done.set()
            if ticket.journaled:
                self._journal_terminal(ticket, ticket.result)
            return
        self.metrics.event("router.failover", rid=ticket.rid,
                           src=old, dst=handle.name, **info)
        if self.on_route is not None:
            self.on_route(handle.name, ticket.req)
        handle.submit(ticket.req,
                      lambda res2, t=ticket: self._on_done(t, res2))

    def _on_replica_death(self, replica: ReplicaHandle) -> None:
        self._mark_dead(replica.name)

    def _mark_dead(self, name: str) -> None:
        with self._lock:
            if name in self._dead:
                return
            self._dead.add(name)
            healthy = len(self.replicas) - len(self._dead)
        self.metrics.counter("router.replica_deaths").inc()
        self.metrics.gauge("router.replicas_healthy").set(healthy)
        self.metrics.event("router.replica_death", replica=name)

    def _mark_alive(self, name: str) -> None:
        """Return a revived replica to the candidate set (poll path
        only, for ``can_revive`` handles whose probes turned healthy)."""
        with self._lock:
            if name not in self._dead:
                return
            self._dead.discard(name)
            healthy = len(self.replicas) - len(self._dead)
        self.metrics.counter("router.replica_revives").inc()
        self.metrics.gauge("router.replicas_healthy").set(healthy)
        self.metrics.event("router.replica_revive", replica=name)

    def replace_replica(self, name: str, handle: ReplicaHandle) -> None:
        """Swap a (dead) replica's handle for a fresh one under the
        same name and return it to the candidate set — the
        supervisor's respawn commit point.  The shadow index survives
        the swap: its paths are phantoms for the fresh engine's empty
        cache (benign — one suboptimal route each) until warm replay
        and the poller's digest feed repopulate it."""
        if isinstance(handle, LocalReplica) and handle.on_death is None:
            handle.on_death = self._on_replica_death
        with self._lock:
            for i, r in enumerate(self.replicas):
                if r.name == name:
                    self.replicas[i] = handle
                    break
            else:
                raise KeyError(name)
            self._probe_fails[name] = 0
            self._views.pop(name, None)
        self._mark_alive(name)

    # -- elastic membership (the autoscaler's actuation surface) -----------

    def cordon_replica(self, name: str) -> None:
        """Remove a replica from the routing candidate set without
        touching its health: no new placements land on it, while its
        in-flight requests keep draining (finish normally, or fail
        open into failover/journal replay if it dies).  Probes, views,
        and the shadow index all keep running, so :meth:`uncordon_replica`
        is a full no-cost undo."""
        with self._lock:
            if not any(r.name == name for r in self.replicas):
                raise KeyError(name)
            if name in self._cordoned:
                return
            self._cordoned.add(name)
        self.metrics.event("router.cordon", replica=name)

    def uncordon_replica(self, name: str) -> None:
        """Return a cordoned replica to the candidate set."""
        with self._lock:
            if name not in self._cordoned:
                return
            self._cordoned.discard(name)
        self.metrics.event("router.uncordon", replica=name)

    def add_replica(self, handle: Any, *,
                    name: str | None = None) -> ReplicaHandle:
        """Join a brand-new replica to the fleet (the autoscaler's
        grow commit point; bare engines wrap like the constructor).
        The newcomer starts with an empty shadow index and zero
        counters and is immediately routable."""
        if not isinstance(handle, ReplicaHandle):
            handle = LocalReplica(handle,
                                  name=name or "replica-new",
                                  faults=self.faults)
        if isinstance(handle, LocalReplica) and handle.on_death is None:
            handle.on_death = self._on_replica_death
        with self._lock:
            if any(r.name == handle.name for r in self.replicas):
                raise ValueError(
                    f"duplicate replica name {handle.name!r}")
            self.replicas.append(handle)
            self._probe_fails[handle.name] = 0
            self._shadows[handle.name] = ShadowPrefixIndex(
                handle.block_size, self.shadow_max_paths)
            self._inflight[handle.name] = 0
            self._routed[handle.name] = 0
            healthy = len(self.replicas) - len(self._dead)
        self.metrics.gauge("router.replicas_healthy").set(healthy)
        self.metrics.event("router.replica_join", replica=handle.name)
        return handle

    def retire_replica(self, name: str, *,
                       stop: bool = True) -> ReplicaHandle:
        """Remove a replica from the fleet entirely (the autoscaler's
        scale-down commit point, after cordon + drain).  The caller
        owns the drain: retiring with in-flight work abandons those
        callbacks, so cordon first and wait for (or force) zero
        inflight.  Returns the removed handle."""
        with self._lock:
            if len(self.replicas) <= 1:
                raise ValueError(
                    "refusing to retire the last replica")
            for i, r in enumerate(self.replicas):
                if r.name == name:
                    handle = self.replicas.pop(i)
                    break
            else:
                raise KeyError(name)
            inflight = self._inflight.pop(name, 0)
            self._routed.pop(name, None)
            self._views.pop(name, None)
            self._shadows.pop(name, None)
            self._probe_fails.pop(name, None)
            self._cordoned.discard(name)
            self._dead.discard(name)
            healthy = len(self.replicas) - len(self._dead)
        self.metrics.gauge("router.replicas_healthy").set(healthy)
        self.metrics.event("router.replica_retire", replica=name,
                           inflight=inflight)
        if stop:
            handle.stop()
        return handle

    def cordoned(self) -> list[str]:
        with self._lock:
            return sorted(self._cordoned)

    # -- the request journal -----------------------------------------------

    def _journal_append(self, kind: str, **fields: Any) -> None:
        """One WAL append, fault-isolated: a failed journal write (the
        ``router.journal`` fault site, or a real disk error) degrades
        durability — counted and evented — but never fails the
        request being served."""
        if self._journal is None:
            return
        try:
            self.faults.check("router.journal", key=kind)
            self._journal.emit(kind, **fields)
        except Exception as e:
            self.metrics.counter("router.journal_errors").inc()
            self.metrics.event("router.journal_error", record=kind,
                               error=str(e))
        else:
            self.metrics.counter("router.journal_appends").inc()

    def _journal_terminal(self, ticket: _Ticket,
                          res: RequestResult) -> None:
        """Record a journaled request's terminal outcome and release
        its idempotency key: the result becomes the exactly-once
        answer for later duplicates, and every ticket parked on the
        key completes with the same result."""
        waiters: list[_Ticket] = []
        with self._lock:
            if ticket.key is not None:
                self._journal_results[ticket.key] = res
                while len(self._journal_results) > self.journal_keys:
                    self._journal_results.pop(
                        next(iter(self._journal_results)))
                self._journal_inflight.pop(ticket.key, None)
                waiters = self._journal_waiters.pop(ticket.key, [])
        self._journal_append(
            "router.terminal", rid=ticket.rid, key=ticket.key,
            status=res.status, tokens=list(res),
            error=None if res.error is None else str(res.error))
        for w in waiters:
            with self._lock:
                if w.done.is_set():
                    continue
                w.result = res
                w.done_ts = self.clock()
            w.done.set()

    def replay_journal(self) -> int:
        """Re-submit every journaled accept with no terminal record
        (crash recovery; :meth:`start` runs this once).  Greedy
        determinism makes each replayed result bit-identical to what
        the lost incarnation would have produced, and keyed requests
        land back in the dedup map so their clients' retries find
        them.  Each replay routes under THIS incarnation's own fresh
        accept record, so once it is durable a ``router.replayed``
        marker retires the original accept — without it the original
        would stay forever unpaired and re-run on every future
        restart, not just this one.  Returns the number of requests
        replayed."""
        pending, self._journal_pending = self._journal_pending, []
        n = 0
        for rec in pending:
            try:
                req = request_from_json(rec.get("req") or {})
            except ValueError:
                # Poisoned or truncated record: it can never replay,
                # so retire it rather than re-parse-and-skip it in
                # every incarnation from now on.
                self._journal_append("router.replayed",
                                     pid=rec.get("pid"),
                                     rid=rec.get("rid"),
                                     key=rec.get("key"), poisoned=True)
                continue
            self.metrics.counter("router.journal_replays").inc()
            self.metrics.event("router.journal_replay",
                               key=rec.get("key"))
            # Rejoin the original trace: the accept record carried the
            # dead incarnation's router.request span, so this replay's
            # span becomes its child — crash-recovery chains render as
            # ONE tree across (pid, rid) incarnations.
            tctx = tracing_mod.TraceContext.from_dict(rec.get("trace"))
            if tctx is not None:
                req.trace_ctx = tctx
            ticket = self._route(req, rec.get("key"))
            if ticket.journaled:
                # The fresh accept hit the WAL inside _route, so the
                # request now survives on its own record; a shed
                # replay (journaled=False) keeps the original accept
                # live for the next incarnation instead.
                self._journal_append("router.replayed",
                                     pid=rec.get("pid"),
                                     rid=rec.get("rid"),
                                     key=rec.get("key"))
            n += 1
        return n

    # -- polling + reports -------------------------------------------------

    def poll_now(self) -> None:
        """One synchronous poll pass (the poller thread's body; tests
        and the bench call it directly for deterministic views).

        Death is debounced for revivable replicas: an HTTP replica
        needs ``probe_fails`` CONSECUTIVE failed probes before it
        leaves the candidate set (one ``/healthz`` blip, or a backend
        still starting at the first 0.05s poll, must not permanently
        shrink the fleet), and a healthy probe brings it back.  A
        local replica's probe is authoritative — its pump thread is
        gone — so it dies on the first unhealthy view and stays dead."""
        # Pass duration is measured on the wall (perf_counter), never
        # the injectable clock: under virtual time the pass itself
        # still costs real host work, and that cost scaling with fleet
        # size is exactly what router.poll_s exists to expose.
        pass_t0 = time.perf_counter()
        for r in list(self.replicas):
            try:
                view = r.probe()
            except Exception:
                view = {"healthy": False}
            healthy = bool(view.get("healthy", False))
            with self._lock:
                self._views[r.name] = view
                self._shadows[r.name].load(view.get("prefix"))
                if healthy:
                    self._probe_fails[r.name] = 0
                else:
                    self._probe_fails[r.name] = \
                        self._probe_fails.get(r.name, 0) + 1
                fails = self._probe_fails[r.name]
            if healthy:
                if r.can_revive:
                    self._mark_alive(r.name)  # no-op when not dead
            elif not r.can_revive or fails >= self.probe_fails:
                self._mark_dead(r.name)       # no-op when already dead
        self.metrics.gauge("router.shadow_index_bytes").set(
            self._enforce_shadow_bound(self._shadow_bytes()))
        sup = self.supervisor
        if sup is not None:
            sup.tick()
        # Health plane rides the poll cadence — cheap no-ops between
        # sampling/evaluation deadlines.
        if self.sampler is not None:
            self.sampler.tick()
            if self.alerts is not None:
                self.alerts.tick()
        asc = self.autoscaler
        if asc is not None:
            asc.tick()
        self.reap_tickets()
        self.metrics.gauge("router.fleet_size").set(len(self.replicas))
        self.metrics.histogram("router.poll_s").observe(
            time.perf_counter() - pass_t0)

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_s):
            self.poll_now()

    def _shadow_bytes(self) -> int:
        with self._lock:
            return sum(s.approx_footprint_bytes()
                       for s in self._shadows.values())

    def _enforce_shadow_bound(self, total: int) -> int:
        """Evict oldest shadow digests until the fleet-wide footprint
        fits ``shadow_max_bytes``.  The per-index ``max_paths`` FIFO
        caps each replica, but at hundreds of replicas the *union* is
        the leak; the poller trims the fattest indexes an eighth at a
        time so steady-state cost is a handful of deque pops, not a
        rebuild.  The running total is decremented by each victim's
        measured shrink rather than re-summed fleet-wide — at 200+
        replicas a full sizeof scan per eviction round turns the poll
        pass quadratic.  Returns the (possibly reduced) total."""
        if self.shadow_max_bytes <= 0:
            return total
        evicted = 0
        while total > self.shadow_max_bytes:
            with self._lock:
                victim = max(self._shadows.values(), key=len,
                             default=None)
                if victim is None or len(victim) == 0:
                    break
                before = victim.approx_footprint_bytes()
                evicted += victim.evict_oldest(max(len(victim) // 8, 1))
                total -= before - victim.approx_footprint_bytes()
        if evicted:
            self.metrics.counter("router.shadow_evictions").inc(evicted)
            self.metrics.event("router.shadow_evict", digests=evicted)
        return total

    def health(self) -> tuple[int, dict]:
        """``GET /healthz``: 200 while at least one replica is
        routable, 503 once the whole fleet is dead.  ``degraded`` is
        true while the fleet runs on its supervisor's restart budget
        (a respawned or circuit-broken replica) — still a 200, but a
        deploy gate should notice."""
        with self._lock:
            healthy = [r.name for r in self.replicas
                       if r.name not in self._dead]
            cordoned = sorted(self._cordoned)
            draining = sorted(n for n in self._cordoned
                              if self._inflight.get(n, 0) > 0)
            body = {"ok": bool(healthy), "replicas": len(self.replicas),
                    "healthy": len(healthy), "pid": os.getpid(),
                    "cordoned": cordoned, "draining": draining}
        sup = self.supervisor
        body["degraded"] = bool(sup is not None and sup.degraded())
        asc = self.autoscaler
        if asc is not None:
            body["epoch"] = asc.epoch.generation
        return (200 if body["ok"] else 503), body

    def state_dump(self) -> str:
        """Human-readable router state (the engine ``state_dump``
        contract one layer up; served at ``GET /state``): per-replica
        health and routing counts, ticket/journal bookkeeping, and —
        with a supervisor attached — each replica's restart history."""
        lines = [f"RouterServer policy={self.policy.name} "
                 f"port={self.port} pid={os.getpid()}"]
        with self._lock:
            n_tickets = len(self._tickets)
            n_done = sum(1 for t in self._tickets.values()
                         if t.done.is_set())
            dead = set(self._dead)
            cordoned = set(self._cordoned)
            rows = [(r.name, self._routed.get(r.name, 0),
                     self._inflight.get(r.name, 0))
                    for r in self.replicas]
            n_keys = len(self._journal_results)
            n_inflight_keys = len(self._journal_inflight)
        lines.append(f"  tickets: {n_tickets} ({n_done} terminal)")
        if self.journal_path:
            lines.append(f"  journal: {self.journal_path} "
                         f"(keys={n_keys} "
                         f"inflight_keys={n_inflight_keys})")
        for name, routed, infl in rows:
            state = "DEAD" if name in dead else "up"
            if name in cordoned:
                state += " CORDONED" + (" draining" if infl else
                                        " drained")
            lines.append(f"  replica {name}: {state} "
                         f"routed={routed} inflight={infl}")
        if self.alerts is not None:
            arep = self.alerts.report()
            lines.append(f"  alerts: firing={arep['firing']} "
                         f"pending={arep['pending']} "
                         f"transitions={len(arep['history'])}")
        if self.advisor is not None:
            rec = self.advisor.recommend()
            lines.append(f"  advice: {rec['action']} n={rec['n']} "
                         f"({rec['reason']})")
        asc = self.autoscaler
        if asc is not None:
            arep = asc.report()
            last = arep["last_action"]
            lines.append(
                f"  autoscaler: epoch={arep['epoch']['generation']} "
                f"size={arep['size']} draining={arep['draining']}"
                + (f" last={last['action']}" if last else ""))
        sup = self.supervisor
        if sup is not None:
            for name, st in sorted(sup.state().items()):
                hist = " ".join("ok" if h["ok"] else "fail"
                                for h in st["history"])
                lines.append(
                    f"  supervisor {name}: "
                    f"restarts={st['restarts']}/{st['max_restarts']}"
                    + (" PERMANENT-DEAD" if st["permanent_dead"] else "")
                    + (f" history=[{hist}]" if hist else ""))
        return "\n".join(lines) + "\n"

    def replicas_report(self) -> list[dict]:
        """``GET /replicas``: per-replica routing/health detail the
        label-less Prometheus names can't carry."""
        out = []
        with self._lock:
            for r in self.replicas:
                shadow = self._shadows[r.name]
                infl = self._inflight.get(r.name, 0)
                out.append({
                    "name": r.name,
                    "healthy": r.name not in self._dead,
                    "cordoned": r.name in self._cordoned,
                    "draining": (r.name in self._cordoned
                                 and infl > 0),
                    "routed": self._routed.get(r.name, 0),
                    "inflight": infl,
                    "view": dict(self._views.get(r.name, {}),
                                 prefix=None),
                    "shadow_paths": len(shadow),
                    "shadow_block_size": shadow.block_size,
                })
        return out

    def device_report(self) -> dict:
        """``GET /device``: fleet view of per-replica device telemetry.
        Only in-process :class:`LocalReplica` engines expose the plane
        directly (an HTTP replica's ``/device`` lives on its own
        monitor); replicas without telemetry are listed by name so the
        fleet summary is honest about its coverage.  MFU aggregates
        skip replicas with no honest peak — the summary's ``mfu_*``
        keys are present only when at least one replica reports one."""
        with self._lock:
            handles = list(self.replicas)
        per: dict[str, dict] = {}
        without: list[str] = []
        for r in handles:
            dev = getattr(getattr(r, "engine", None), "device", None)
            if dev is None:
                without.append(r.name)
            else:
                per[r.name] = dev.report()
        out: dict[str, Any] = {
            "replicas": per,
            "without_telemetry": sorted(without),
        }
        mfus = [rep["win"]["mfu"] for rep in per.values()
                if rep["win"]["mfu"] is not None]
        summary: dict[str, Any] = {
            "n_reporting": len(per),
            "fleet_flops_per_s": sum(
                rep["win"]["flops_per_s"] for rep in per.values()),
        }
        if mfus:
            summary["mfu_min"] = min(mfus)
            summary["mfu_max"] = max(mfus)
            summary["mfu_mean"] = sum(mfus) / len(mfus)
        out["summary"] = summary
        return out

    def memory_report(self) -> dict:
        """Host-side footprint of the router's own bookkeeping — the
        shadow indexes dominate; ``approx_footprint_bytes`` is their
        sum (also the ``router.shadow_index_bytes`` gauge)."""
        with self._lock:
            per_replica = {n: s.approx_footprint_bytes()
                           for n, s in self._shadows.items()}
            tickets = len(self._tickets)
        total = sum(per_replica.values())
        self.metrics.gauge("router.shadow_index_bytes").set(total)
        return {"approx_footprint_bytes": total,
                "shadow_index_bytes": per_replica,
                "tickets": tickets}


def maybe_start_router(replicas: Sequence[Any],
                       **kwargs: Any) -> RouterServer | None:
    """Start a front door when ``HVD_TPU_ROUTER_PORT`` is set (the
    ``maybe_start_monitor`` contract: unset → None silently,
    unparsable/taken port → warn, never crash the job)."""
    raw = os.environ.get("HVD_TPU_ROUTER_PORT")
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        warnings.warn(f"HVD_TPU_ROUTER_PORT={raw!r} is not an int; "
                      "router disabled", RuntimeWarning, stacklevel=2)
        return None
    try:
        return RouterServer(replicas, port=port, **kwargs).start()
    except OSError as e:
        warnings.warn(f"router port {port} unavailable ({e}); "
                      "router disabled", RuntimeWarning, stacklevel=2)
        return None


# ---------------------------------------------------------------------------
# Bench arm: affinity routing vs round robin over an in-process fleet.
# ---------------------------------------------------------------------------


def measure_router_fleet(
    params: dict, cfg: Any, *,
    n_replicas: int = 3, n_groups: int = 4, waves: int = 6,
    prefix_blocks: int = 4, suffix_len: int = 4,
    max_new_tokens: int = 8, n_slots: int = 4,
    chunk: int = 16, max_len: int | None = None,
    policies: Sequence[str] = ("round_robin", "prefix_affinity"),
) -> dict:
    """Fleet prefix hit rate and throughput, affinity vs round robin
    (the ``serve_router_*`` bench metrics).

    The workload is ``n_groups`` families sharing a
    ``prefix_blocks * chunk``-token prefix, submitted in ``waves``
    rounds of one request per family (each wave waits for the previous
    — the steady drip of a production prompt family, and it makes hit
    accounting deterministic).  Keep ``n_groups`` non-multiple of
    ``n_replicas``: with ``G == R`` round robin aligns each family to
    one replica by accident and the contrast vanishes.  Each policy serves the identical
    workload on a fresh ``n_replicas``-engine fleet whose programs are
    pre-compiled by an untimed disjoint warmup, so the timed passes
    compare *routing* — affinity concentrates each family on one
    replica (first wave misses, the rest hit); round robin smears it
    across the fleet (one cold miss per replica per family).  Outputs
    are asserted token-identical across policies (routing must never
    change tokens).  Returns per-policy
    ``serve_router_hit_rate_<policy>`` /
    ``serve_router_tokens_per_sec_<policy>`` plus the affinity-minus-
    round-robin ``serve_router_hit_rate_gain`` and workload shape."""
    from horovod_tpu.serving_scheduler import ServeEngine

    prefix_len = prefix_blocks * chunk
    if max_len is None:
        need = prefix_len + suffix_len + max_new_tokens + chunk
        max_len = -(-need // chunk) * chunk     # block-aligned
    workload: list[Request] = []
    for w in range(waves):
        for g in range(n_groups):
            prefix = [(11 + 13 * g + i) % 89 + 2
                      for i in range(prefix_len)]
            suffix = [(29 + 7 * g + 3 * w + i) % 89 + 2
                      for i in range(suffix_len)]
            workload.append(Request(prompt=prefix + suffix,
                                    max_new_tokens=max_new_tokens))

    out: dict[str, Any] = {
        "serve_router_replicas": n_replicas,
        "serve_router_groups": n_groups,
        "serve_router_waves": waves,
        "n_requests": len(workload),
        "chunk": chunk,
        "n_slots": n_slots,
    }
    outputs: dict[str, list[list[int]]] = {}
    for policy in policies:
        engines = [ServeEngine(params, cfg, n_slots=n_slots,
                               max_len=max_len, chunk=chunk,
                               prefix_cache=True)
                   for _ in range(n_replicas)]
        # Untimed warmup: compile every program with a token family the
        # workload never shares a first chunk with, so the timed hit
        # counters start from a cold radix for the measured prompts.
        for eng in engines:
            warm = eng.run([Request(prompt=[1] * (chunk + 1),
                                    max_new_tokens=2)])
            assert all(r.ok for r in warm)
        router = RouterServer(engines, policy=policy)
        try:
            hits0 = sum(e.prefix_counters["hits"] for e in engines)
            toks: list[list[int]] = []
            t0 = time.perf_counter()
            for w in range(waves):
                wave = workload[w * n_groups:(w + 1) * n_groups]
                rids = [router.route(r) for r in wave]
                toks.extend(list(router.result(rid)) for rid in rids)
            dt = time.perf_counter() - t0
            hits = sum(e.prefix_counters["hits"] for e in engines) - hits0
            n_tokens = sum(len(t) for t in toks)
            outputs[policy] = toks
            out[f"serve_router_hit_rate_{policy}"] = hits / len(workload)
            out[f"serve_router_tokens_per_sec_{policy}"] = n_tokens / dt
        finally:
            router.stop()
    first = next(iter(outputs))
    for policy, toks in outputs.items():
        assert toks == outputs[first], \
            f"routing changed tokens: {first} vs {policy}"
    if "round_robin" in outputs and "prefix_affinity" in outputs:
        out["serve_router_hit_rate_gain"] = (
            out["serve_router_hit_rate_prefix_affinity"]
            - out["serve_router_hit_rate_round_robin"])
    return out
