"""Collective ops: in-graph (SPMD) and eager (rank-major) flavors."""

from horovod_tpu.ops.collective_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    ProcessSet,
    Product,
    Sum,
    adasum_allreduce,
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    grouped_allreduce,
    reducescatter,
)
