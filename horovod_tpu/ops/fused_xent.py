"""Fused linear + cross-entropy — the vocab-projection loss without the
[N, V] logits tensor.

The standard LM loss materializes fp32 logits [B·L, V] plus a log-softmax
copy; at (batch 4, seq 2048, vocab 128k) that is ~8 GB of HBM for ONE
intermediate, and it bounds the trainable batch long before the MXU is
busy.  The fused form streams the lm_head in vocab chunks with an online
logsumexp (the softmax trick flash attention uses along keys, applied to
the class axis):

    for each chunk c of W[:, off:off+C]:
        logits_c = x @ W_c                       # [N, C] — the only big live
        m, s     = online-max / scaled sumexp    # [N]
        tgt      = target logit when target ∈ c  # [N]
    loss = mean(m + log s − tgt)

Peak memory drops from O(N·V) to O(N·C); FLOPs are identical (every
W column is visited once).  The chunk body is rematerialized, so backward
recomputes each chunk's logits instead of saving them — the same
compute/memory trade as ``jax.checkpoint`` on a transformer layer.

No reference equivalent (its model zoo ends at word2vec-scale softmax,
e.g. the sampled-softmax NCE in examples/tensorflow_word2vec.py); this is
a TPU-scale extension used by the Llama family
(``LlamaConfig.fused_loss_chunk``).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def fused_linear_cross_entropy(
    x: jax.Array,
    w: jax.Array,
    targets: jax.Array,
    *,
    chunk_size: int = 8192,
) -> jax.Array:
    """Mean cross-entropy of ``softmax(x @ w)`` against ``targets``.

    x: [N, D] final hidden states (any float dtype; matmul accumulates
    fp32).  w: [D, V] vocab projection.  targets: [N] int class ids.
    ``chunk_size`` columns of ``w`` are processed per step (clamped to V).
    """
    n, d = x.shape
    v = w.shape[1]
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive, got {chunk_size}")
    c = min(chunk_size, v)
    nchunks = -(-v // c)
    offsets = jnp.arange(nchunks) * c

    def body(carry, off):
        m, s, tgt = carry
        # dynamic_slice clamps an out-of-range start; make that explicit so
        # the ragged final chunk's window [start, start+C) is known, and
        # mask to the LOGICAL chunk [off, min(off+C, V)) — the clamped
        # window re-reads columns the previous chunk already counted.
        start = jnp.minimum(off, v - c)
        wc = lax.dynamic_slice_in_dim(w, start, c, axis=1)      # [D, C]
        logits = jax.lax.dot_general(
            x, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # [N, C]
        cols = start + jnp.arange(c)[None, :]
        valid = (cols >= off) & (cols < v)
        logits = jnp.where(valid, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]
        ).sum(axis=-1)
        in_chunk = (targets >= off) & (targets < off + c)
        idx = jnp.clip(targets - start, 0, c - 1)
        tl = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        tgt = jnp.where(in_chunk, tl, tgt)
        return (m_new, s, tgt), None

    init = (
        jnp.full((n,), NEG_INF, jnp.float32),   # running max
        jnp.zeros((n,), jnp.float32),           # scaled sumexp
        jnp.full((n,), NEG_INF, jnp.float32),   # target logit
    )
    # Remat the chunk body: backward recomputes each chunk's [N, C] logits
    # instead of the scan saving all nchunks of them (which would rebuild
    # the exact [N, V] residency this function exists to avoid).
    (m, s, tgt), _ = lax.scan(jax.checkpoint(body), init, offsets)
    return jnp.mean(m + jnp.log(s) - tgt)


def reference_cross_entropy(x, w, targets) -> jax.Array:
    """The unfused oracle (materializes [N, V]); tests compare against it."""
    logits = (x @ w).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, targets[:, None], axis=-1))
