"""Gradient compression algorithms.

Parity with the reference's ``Compression`` registry
(reference: horovod/tensorflow/compression.py:1-74 and
horovod/torch/compression.py:1-74), extended with the fork's top-k sparse
scheme (reference: horovod/torch/__init__.py:46-83, 141-151, 202-216) as a
first-class compressor.

TPU notes: the natural 16-bit wire type on TPU is **bfloat16** (same exponent
range as fp32, MXU-native).  ``Compression.fp16`` keeps the reference's name
and uses float16 for bit-parity; ``Compression.bf16`` is the TPU-preferred
variant.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class Compressor:
    """Interface: compress before the wire transfer, decompress after.

    Mirrors reference compression.py:23-44.
    """

    @staticmethod
    def compress(tensor: jax.Array) -> tuple[jax.Array, Any]:
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: jax.Array, ctx: Any) -> jax.Array:
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference compression.py:47-57)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        del ctx
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: Any = None

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast-down to float16 for the transfer, cast back after
    (reference compression.py:60-74)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """TPU-native 16-bit wire format: bfloat16 keeps fp32's exponent range so
    gradient all-reduce needs no loss-scaling."""

    wire_dtype = jnp.bfloat16


class TopKContext(NamedTuple):
    shape: tuple
    dtype: Any
    k: int


class TopKCompressor:
    """Top-k sparse gradients — the fork's headline feature, TPU-style.

    The fork compresses by picking the k largest-magnitude entries and
    allgathering ``(values ‖ indices)`` with mpi4py, then scatter-adding on
    every rank (reference horovod/torch/__init__.py:46-83).  The TPU-native
    form does the same dataflow inside one compiled program:
    ``lax.top_k`` on |flat gradient| → ``all_gather(values, indices)`` →
    ``scatter-add`` into a dense buffer, all fused by XLA.

    Unlike the dense compressors this changes the *collective* (allgather
    instead of allreduce), so it exposes :meth:`sparse_allreduce` and the
    ``Compressor`` interface raises if used on the dense path.
    """

    def __init__(self, ratio: float = 0.01, k: int | None = None):
        self.ratio = ratio
        self.k = k

    def _k_for(self, n: int) -> int:
        if self.k is not None:
            return max(1, min(self.k, n))
        return max(1, min(n, int(n * self.ratio)))

    def compress(self, tensor):
        raise NotImplementedError(
            "TopKCompressor changes the collective; use sparse_allreduce()."
        )

    decompress = compress

    def sparse_allreduce(self, tensor: jax.Array, *, average: bool = False,
                         axis_name: str = "hvd") -> jax.Array:
        flat = tensor.reshape(-1)
        n = flat.shape[0]
        k = self._k_for(n)
        vals, idxs = lax.top_k(jnp.abs(flat), k)
        del vals
        picked = flat[idxs]
        all_vals = lax.all_gather(picked, axis_name, tiled=True)     # [size*k]
        all_idxs = lax.all_gather(idxs, axis_name, tiled=True)       # [size*k]
        dense = jnp.zeros_like(flat).at[all_idxs].add(all_vals)
        if average:
            dense = dense / lax.axis_size(axis_name)
        return dense.reshape(tensor.shape)


class Int8Compressor(Compressor):
    """8-bit quantized all-reduce (a TPU-native extension in the fork's
    gradient-compression spirit, reference horovod/torch/__init__.py:46-83).

    **Per-block** max-abs scaling to int8 (round-to-nearest, 1024-element
    blocks), then the *collective itself* changes: an int8 ``all_gather``
    moves ~(n-1)/n·S/4 bytes per link on a ring versus ~2·S·(n-1)/n for an
    fp32 all-reduce — an ~8× wire saving — and every rank dequantizes and
    sums locally in fp32, so no int8 overflow can occur.  Block-granular
    scales matter because the fusion path concatenates many tensors into one
    buffer before compressing (ops/fusion.py): one global scale would let a
    large-magnitude layer zero out a small-magnitude one; with blocks, each
    element's quantization step is bounded by its own 1024-neighborhood's
    max-abs (error ≤ size · block_maxabs/254 per element).

    Like :class:`TopKCompressor` this cannot be used on the plain dense
    path; :func:`collective_ops.allreduce` dispatches to
    :meth:`quantized_allreduce` automatically.
    """

    BLOCK = 1024

    @staticmethod
    def compress(tensor):
        raise NotImplementedError(
            "quantized compressors change the collective; pass them to "
            "allreduce() (compression=Compression.int8/int4), which "
            "dispatches automatically."
        )

    decompress = compress

    # -- wire format hooks (overridden by Int4Compressor) ------------------

    @classmethod
    def _encode(cls, x: jax.Array, scale: jax.Array) -> jax.Array:
        """f32 block values [nb, B] → wire codes."""
        return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)

    @classmethod
    def _decode(cls, codes: jax.Array, scale: jax.Array) -> jax.Array:
        """wire codes → f32 block values [nb, B] (already × scale)."""
        return codes.astype(jnp.float32) * scale

    # 1/LEVELS of the block's max-abs is the quantization step.
    LEVELS = 127.0

    @classmethod
    def _scale_for(cls, x: jax.Array) -> jax.Array:
        """Block scales for ``x`` [nb, B] — part of the single wire
        definition (all-zero blocks guarded)."""
        return jnp.maximum(
            jnp.max(jnp.abs(x), axis=1, keepdims=True) / cls.LEVELS, 1e-30
        )

    @classmethod
    def _block_quantize(cls, tensor: jax.Array, *, block_multiple: int = 1):
        """The wire's quantizer — THE single definition of the format.

        Returns ``(codes [nb, ...], scale f32 [nb, 1], n)`` where ``n`` is
        the unpadded flat length.  Both the collective (one- AND two-shot;
        ``block_multiple`` pads the block count so ranks own equal shards)
        and the error-feedback residual (ops/powersgd.py) go through here,
        so the residual can never drift from what the wire actually
        carried.
        """
        flat = tensor.astype(jnp.float32).reshape(-1)
        n = flat.shape[0]
        nblocks = -(-n // cls.BLOCK)
        nblocks += (-nblocks) % block_multiple
        pad = nblocks * cls.BLOCK - n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        x = flat.reshape(nblocks, cls.BLOCK)
        scale = cls._scale_for(x)
        return cls._encode(x, scale), scale, n

    @classmethod
    def roundtrip(cls, tensor: jax.Array) -> jax.Array:
        """quant→dequant of ``tensor`` through the exact wire format — what
        this rank's contribution looks like after the collective.

        Models the FIRST quantization only: on the two-shot path the
        reduced shard is rounded a second time before the all-gather, a
        component an ErrorFeedback residual built from this estimate does
        not see (it is bounded by one quantization step of the SUM, and
        shrinks as gradients do)."""
        codes, scale, n = cls._block_quantize(tensor)
        out = cls._decode(codes, scale).reshape(-1)[:n]
        return out.reshape(tensor.shape)

    # Above this world size the two-shot path is the default: received
    # wire is ~2C vs the one-shot all-gather's (n-1)·C, so one-shot only
    # competes in tiny worlds (and costs one fewer rounding step there).
    TWO_SHOT_MIN_WORLD = 5

    @classmethod
    def one_shot(cls):
        """Variant pinned to the one-shot wire at every world size.

        The ErrorFeedback path uses this: ``roundtrip`` models the first
        quantization exactly, so with one-shot the residual matches the
        wire bit-for-bit; the two-shot path's second rounding would leak
        past the residual — the exact bias EF exists to eliminate."""
        v = cls.__dict__.get("_one_shot_variant")
        if v is None:
            v = type(cls.__name__ + "OneShot", (cls,),
                     {"TWO_SHOT_MIN_WORLD": 1 << 62})
            cls._one_shot_variant = v
        return v

    @classmethod
    def quantized_allreduce(cls, tensor: jax.Array, *, average: bool = False,
                            axis_name="hvd",
                            two_shot: bool | None = None) -> jax.Array:
        """Quantized all-reduce with a scale-aware wire.

        Two dataflows, auto-selected by world size (``two_shot=None``):

        * **one-shot** (small worlds): all_gather the codes+scales, every
          rank dequantizes and sums in fp32.  Received bytes: ``(n-1)·C``
          where C is the compressed payload — past a handful of ranks the
          "compression" moves more wire than an uncompressed psum.
        * **two-shot** (``n >= TWO_SHOT_MIN_WORLD``): quantized
          reduce-scatter then quantized all-gather — the ZeRO++-style
          scheme.  Each rank all-to-alls its code shards (receives
          ``(n-1)/n·C``), dequant-sums its shard in fp32, REQUANTIZES the
          partial sum, and all-gathers the compressed shard (another
          ``(n-1)/n·C``): ~``2C`` received regardless of n, at the cost of
          a second rounding step (wrap in ErrorFeedback for bias-freedom).

        Tuple axis names (hierarchical meshes) always take the one-shot
        path: the shard exchange is defined over a single flat axis.
        """
        orig_dtype, orig_shape = tensor.dtype, tensor.shape
        flat_axis = not isinstance(axis_name, (tuple, list))
        if two_shot is None:
            two_shot = False
            if flat_axis:
                sz = lax.axis_size(axis_name)
                nb1 = -(-int(tensor.size) // cls.BLOCK)
                nb2 = nb1 + (-nb1) % sz      # padded to equal shards
                # Only when it actually saves wire: one-shot receives
                # (n-1)·nb1 blocks, two-shot ~2·nb2 (tiny tensors pad up
                # and would move MORE with an extra rounding on top).
                two_shot = (sz >= cls.TWO_SHOT_MIN_WORLD
                            and (sz - 1) * nb1 > 2 * nb2)
        if two_shot and not flat_axis:
            raise ValueError(
                "two-shot quantized allreduce needs a single flat axis; "
                f"got axis_name={axis_name!r}"
            )
        if not two_shot:
            codes, scale, n = cls._block_quantize(tensor)
            all_q = lax.all_gather(codes, axis_name)   # [size, nb, ...] wire
            all_s = lax.all_gather(scale, axis_name)   # [size, nb, 1] f32
            summed = jnp.sum(
                jax.vmap(cls._decode)(all_q, all_s), axis=0
            )
            if average:
                summed = summed / all_q.shape[0]  # works for tuple axes too
            out = summed.reshape(-1)[:n]
            return out.reshape(orig_shape).astype(orig_dtype)

        size = lax.axis_size(axis_name)
        # The shared wire quantizer, block count padded to a multiple of
        # the world size so every rank owns an equal shard of blocks.
        codes, scale, n = cls._block_quantize(tensor, block_multiple=size)
        m = codes.shape[0] // size
        # Shot 1 — quantized reduce-scatter: exchange code shards so rank r
        # holds every rank's blocks [r*m, (r+1)*m), then dequant-sum fp32.
        sh_codes = codes.reshape(size, m, codes.shape[-1])
        sh_scale = scale.reshape(size, m, 1)
        recv_codes = lax.all_to_all(
            sh_codes, axis_name, split_axis=0, concat_axis=0, tiled=True
        )                                           # [size, m, .] wire
        recv_scale = lax.all_to_all(
            sh_scale, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
        part = jnp.sum(jax.vmap(cls._decode)(recv_codes, recv_scale), axis=0)
        if average:
            part = part / size                      # [m, B] fp32 shard sum

        # Shot 2 — requantize the reduced shard, all-gather the codes.
        scale2 = cls._scale_for(part)
        codes2 = cls._encode(part, scale2)
        all_q = lax.all_gather(codes2, axis_name)   # [size, m, .] wire
        all_s = lax.all_gather(scale2, axis_name)
        full = jax.vmap(cls._decode)(all_q, all_s).reshape(-1)[:n]
        return full.reshape(orig_shape).astype(orig_dtype)


class Int4Compressor(Int8Compressor):
    """4-bit quantized all-reduce: two codes per byte — half int8's wire
    (~16× less than fp32), same per-1024-block max-abs scaling and the
    same all-gather + local fp32 dequant-sum dataflow.  Codes live in
    [-7, 7] (scale = block max-abs / 7) packed as ``lo | hi<<4`` uint8;
    accuracy-sensitive jobs should wrap it in
    :class:`~horovod_tpu.ops.powersgd.ErrorFeedback`, which makes the
    aggressive rounding unbiased over time."""

    LEVELS = 7.0

    @classmethod
    def _encode(cls, x, scale):
        q = (jnp.clip(jnp.round(x / scale), -7, 7) + 8).astype(jnp.uint8)
        pairs = q.reshape(q.shape[0], -1, 2)       # [nb, B/2, 2]
        return pairs[:, :, 0] | (pairs[:, :, 1] << 4)

    @classmethod
    def _decode(cls, codes, scale):
        lo = (codes & 0xF).astype(jnp.int32) - 8
        hi = (codes >> 4).astype(jnp.int32) - 8
        q = jnp.stack([lo, hi], axis=-1).reshape(codes.shape[0], -1)
        return q.astype(jnp.float32) * scale


class Compression:
    """Registry, parity with reference compression.py:70-74."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    topk = TopKCompressor
    int8 = Int8Compressor
    int4 = Int4Compressor
