"""Async-handle bookkeeping for the eager frontend.

Parity with the reference's ``HandleManager``
(reference: horovod/torch/handle_manager.h/.cc:22-53): atomic int handles
mapped to results, backing Python ``poll()`` / ``synchronize()``.

The TPU twist: JAX dispatch is *already* asynchronous — a dispatched
collective returns a ``jax.Array`` future immediately.  A handle therefore
moves through three states:

  QUEUED      enqueued, waiting for the engine cycle to fuse + dispatch it
  DISPATCHED  a jax.Array future exists; the chip may still be computing
  DONE        result materialized (or an error captured)
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any


@dataclass
class _Entry:
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None           # jax.Array once dispatched
    error: BaseException | None = None
    dispatched: bool = False
    name: str | None = None      # tensor name, for timeline attribution
    post: Any = None             # frontend post-processing payload (opaque)


class HandleManager:
    _GUARDED_BY_LOCK = ("_entries",)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counter = itertools.count()
        self._entries: dict[int, _Entry] = {}

    def allocate(self, name: str | None = None) -> int:
        """reference handle_manager.cc:22-27."""
        h = next(self._counter)
        with self._lock:
            self._entries[h] = _Entry(name=name)
        return h

    def name(self, handle: int) -> str | None:
        with self._lock:
            e = self._entries.get(handle)
            return e.name if e is not None else None

    def set_post(self, handle: int, payload: Any) -> None:
        """Attach a frontend post-processing payload to a live handle.

        The payload lives and dies with the entry — released by ``wait``,
        ``release``, and error paths alike — so frontends need no side
        tables keyed by handle (which leak when ``synchronize`` raises or a
        caller abandons a handle)."""
        with self._lock:
            e = self._entries.get(handle)
            if e is not None:
                e.post = payload

    def update_post(self, handle: int, items: dict) -> None:
        """Merge keys into a dict-valued post payload — one atomic
        read-modify-write under the manager lock (a take/set pair would
        race a concurrent release and resurrect the payload on a dead or
        recycled handle)."""
        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                return
            if not isinstance(e.post, dict):
                e.post = {}
            e.post.update(items)

    def take_post(self, handle: int) -> Any:
        """Detach and return the handle's post payload (None if absent)."""
        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                return None
            payload, e.post = e.post, None
            return payload

    def _get(self, handle: int) -> _Entry:
        with self._lock:
            try:
                return self._entries[handle]
            except KeyError:
                raise ValueError(
                    f"handle {handle} is unknown or already released"
                ) from None

    def mark_dispatched(self, handle: int, result: Any) -> None:
        # Tolerate released handles: an error-path release() can drop a
        # handle while its _PendingOp is still queued in the engine; the
        # eventual dispatch must not blow up mid-batch (which would leave
        # fused-group peers unmarked and their waiters blocked forever).
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            return
        e.result = result
        e.dispatched = True
        e.event.set()

    def mark_error(self, handle: int, err: BaseException) -> None:
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            return
        e.error = err
        e.dispatched = True
        e.event.set()

    def poll(self, handle: int) -> bool:
        """Non-blocking readiness check (reference handle_manager.cc:35-39 +
        the cudaEventQuery-style probe of ready_event.cc:34-92, which on TPU
        is ``jax.Array.is_ready()``)."""
        e = self._get(handle)
        if not e.event.is_set():
            return False
        if e.error is not None:
            return True
        r = e.result
        if hasattr(r, "is_ready"):
            try:
                return bool(r.is_ready())
            except Exception:
                return True
        return True

    def wait(self, handle: int, flush) -> Any:
        """Block until done, release the handle, return the result.

        ``flush`` is called first so queued-but-unfused work cannot deadlock —
        the analogue of the reference's WaitAndClear poll loop
        (torch/mpi_ops_v2.cc:228-234) except no polling is needed: we block
        on the dispatch event, then on the device future.
        """
        flush()
        e = self._get(handle)
        e.event.wait()
        try:
            if e.error is not None:
                raise e.error
            result = e.result
            if hasattr(result, "block_until_ready"):
                result.block_until_ready()
            return result
        finally:
            self.release(handle)

    def release(self, handle: int) -> None:
        with self._lock:
            self._entries.pop(handle, None)

    def outstanding(self) -> int:
        with self._lock:
            return len(self._entries)
