"""Stateful gradient compression: error feedback + PowerSGD.

The fork's top-k scheme (reference horovod/torch/__init__.py:46-83,
141-151) drops the (1−ratio) smallest gradient entries every step, which
biases the descent direction.  The standard correction — kept by every
production compressed-DP stack since — is **error feedback** (EF14/EF-SGD):
remember the part of the gradient the wire dropped and add it back before
compressing the next step.  **PowerSGD** (Vogels et al., 2019) is the
strongest practical compressor in this family: a rank-``r`` approximation
of each gradient matrix maintained by one warm-started power iteration, at
the cost of two small all-reduces instead of one large one.

Both are *stateful* (residuals; warm-started ``Q`` factors), which the
reference's stateless ``Compressor`` interface cannot express.  The
TPU-native home for that state is the optimizer state pytree: classes here
implement the **stateful-compressor protocol**

    init(grads_template)                       -> comp_state
    reduce(grads, comp_state, *, axis_name, op) -> (reduced, comp_state)

and :func:`horovod_tpu.DistributedOptimizer` threads the state through the
compiled train step — everything stays inside the one SPMD program, so XLA
overlaps the small PowerSGD all-reduces with backward just like the plain
psum path.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.basics import AXIS_NAME
from horovod_tpu.ops.collective_ops import _axis_size
from horovod_tpu.ops.compression import TopKCompressor


class ErrorFeedback:
    """Residual-corrected lossy all-reduce (EF-SGD / EF14).

    Wraps a lossy compressor ``inner`` — :class:`TopKCompressor` or any
    quantized-wire compressor exposing ``quantized_allreduce`` +
    ``roundtrip`` (:class:`Int8Compressor`, :class:`Int4Compressor`) —
    and keeps one residual per gradient leaf:

        corrected = grad + residual
        reduced   = lossy_allreduce(corrected)
        residual' = corrected − transmitted(corrected)

    where ``transmitted`` is what this rank actually contributed to the
    wire (its own top-k entries / its own dequantized int8 blocks).  The
    compression error therefore re-enters the next step instead of being
    lost, which restores SGD's convergence rate under arbitrarily
    aggressive compression.
    """

    def __init__(self, inner):
        cls = inner if isinstance(inner, type) else type(inner)
        quantized = callable(getattr(cls, "quantized_allreduce", None)) and (
            callable(getattr(cls, "roundtrip", None))
        )
        if not (issubclass(cls, TopKCompressor) or quantized):
            raise TypeError(
                "ErrorFeedback supports the lossy wire compressors "
                f"(topk / int8 / int4); got {inner!r}. Dense cast "
                "compressors (fp16/bf16) lose nothing an allreduce can "
                "recover — use them directly."
            )
        if isinstance(inner, type):
            inner = inner()
        self.inner = inner

    def init(self, grads_template) -> Any:
        return jax.tree.map(
            lambda g: jnp.zeros(g.shape, jnp.float32), grads_template
        )

    def transmitted(self, corrected: jax.Array) -> jax.Array:
        """What ONE rank's wire contribution to THIS tensor looks like
        after the lossy compressor — the single definition of the residual
        base that both the compiled path (``reduce``) and the eager hook
        path (EagerDistributedOptimizer) share, so the two can never
        desynchronize."""
        if isinstance(self.inner, TopKCompressor):
            flat = corrected.reshape(-1)
            k = self.inner._k_for(flat.shape[0])
            _, idxs = lax.top_k(jnp.abs(flat), k)
            return (
                jnp.zeros_like(flat).at[idxs].set(flat[idxs])
                .reshape(corrected.shape)
            )
        return type(self.inner).roundtrip(corrected)

    def _reduce_leaf(self, g, e, axis_name, average):
        corrected = g.astype(jnp.float32) + e
        residual = corrected - self.transmitted(corrected)
        if isinstance(self.inner, TopKCompressor):
            flat = corrected.reshape(-1)
            k = self.inner._k_for(flat.shape[0])
            _, idxs = lax.top_k(jnp.abs(flat), k)
            picked = flat[idxs]
            all_vals = lax.all_gather(picked, axis_name, tiled=True)
            all_idxs = lax.all_gather(idxs, axis_name, tiled=True)
            dense = jnp.zeros_like(flat).at[all_idxs].add(all_vals)
            if average:
                dense = dense / _axis_size(axis_name)
            return dense.reshape(corrected.shape).astype(g.dtype), residual
        # int8: residual is this rank's own quantization error, computed by
        # the wire's own quantizer so the two can never drift.  One-shot is
        # forced (via the one_shot() variant when the compressor offers
        # one — third-party protocol conformers keep their own default):
        # the residual models the FIRST quantization exactly, and the
        # two-shot path's second rounding would leak past it.
        cls = type(self.inner)
        if callable(getattr(cls, "one_shot", None)):
            cls = cls.one_shot()
        reduced = cls.quantized_allreduce(
            corrected, average=average, axis_name=axis_name
        )
        return reduced.astype(g.dtype), residual

    def reduce(self, grads, state, *, axis_name=AXIS_NAME, average=True):
        flat_g, treedef = jax.tree.flatten(grads)
        flat_e = treedef.flatten_up_to(state)
        outs = [
            self._reduce_leaf(g, e, axis_name, average)
            for g, e in zip(flat_g, flat_e)
        ]
        reduced = jax.tree.unflatten(treedef, [o[0] for o in outs])
        new_state = jax.tree.unflatten(treedef, [o[1] for o in outs])
        return reduced, new_state


class _PowerSGDLeafState(NamedTuple):
    q: jax.Array          # [m, r] warm-started right factor
    residual: jax.Array   # [n, m] error-feedback memory


def _dense_sentinel() -> jax.Array:
    """Marks a leaf that stays on the exact dense path.  An empty array —
    not ``None`` — because the state rides inside the jitted optimizer
    state, where every pytree leaf must be an array."""
    return jnp.zeros((0,), jnp.float32)


def _matrix_shape(shape: tuple) -> tuple[int, int]:
    """Squarest 2-D view of a gradient: split the dims at the point that
    best balances rows vs columns (conv kernels [h,w,ci,co] become
    [h·w·ci, co]-ish, which is where their low-rank structure lives)."""
    best, best_gap = (1, 1), None
    prod = 1
    for d in shape:
        prod *= d
    left = 1
    for i in range(len(shape) + 1):
        n, m = left, prod // left
        gap = abs(n - m)
        if best_gap is None or gap < best_gap:
            best, best_gap = (n, m), gap
        if i < len(shape):
            left *= shape[i]
    return best


def _orthonormalize(p: jax.Array) -> jax.Array:
    """Gram–Schmidt columns of ``p`` [n, r] — r is tiny, so the loop
    unrolls to r VPU passes; avoids QR's host callbacks on TPU.

    A column that is (numerically) dependent on the previous ones — the
    gradient's true rank is below the compressor's budget — is ZEROED, not
    normalized: dividing its ~0 norm would amplify cancellation noise into
    a garbage direction and corrupt the projection P̂P̂ᵀ."""
    cols = []
    scale = jnp.maximum(jnp.max(jnp.linalg.norm(p, axis=0)), 1e-20)
    for i in range(p.shape[1]):
        c = p[:, i]
        for prev in cols:
            c = c - jnp.dot(prev, c) * prev
        norm = jnp.linalg.norm(c)
        c = jnp.where(
            norm > 1e-6 * scale,
            c / jnp.maximum(norm, 1e-20),
            jnp.zeros_like(c),
        )
        cols.append(c)
    return jnp.stack(cols, axis=1)


class PowerSGDCompressor:
    """Rank-``r`` PowerSGD all-reduce with warm start + error feedback.

    Per 2-D-able gradient ``M`` [n, m] (others go dense):

        M ← grad + residual
        P = M·Q;  P ← mean over ranks;  P̂ = orthonormalize(P)
        Q = Mᵀ·P̂; Q ← mean over ranks
        M̂ = P̂·Qᵀ;  residual ← M − M̂

    Wire cost per step is ``r·(n+m)`` floats instead of ``n·m`` — for a
    4096×4096 layer at r=4 that is ~512× less traffic — and the warm-started
    power iteration makes successive approximations track the dominant
    gradient subspace.  ``min_compress_size`` keeps tiny leaves (biases,
    norms) on the exact dense path, like the reference keeps small tensors
    out of its sparse path.
    """

    def __init__(self, rank: int = 4, min_compress_size: int = 4096,
                 seed: int = 0):
        self.rank = rank
        self.min_compress_size = min_compress_size
        self.seed = seed

    def _compresses(self, g) -> bool:
        if g.size < self.min_compress_size:
            return False
        # A degenerate [1, N] view compresses to N+1 floats — MORE wire than
        # the N-float psum it replaces.  Such leaves (1-D biases, fused
        # vectors) stay on the exact dense path.
        n, m = _matrix_shape(g.shape)
        return min(n, m) > 1

    def init(self, grads_template) -> Any:
        leaves, treedef = jax.tree.flatten(grads_template)
        states = []
        for i, g in enumerate(leaves):
            if not self._compresses(g):
                states.append(_dense_sentinel())
                continue
            n, m = _matrix_shape(g.shape)
            r = min(self.rank, n, m)
            q = jax.random.normal(
                jax.random.key(self.seed + i), (m, r), jnp.float32
            )
            states.append(_PowerSGDLeafState(
                q=q, residual=jnp.zeros((n, m), jnp.float32)
            ))
        return jax.tree.unflatten(treedef, states)

    def _reduce_leaf(self, g, st, axis_name, average):
        if not isinstance(st, _PowerSGDLeafState):   # dense sentinel
            out = lax.psum(g, axis_name)
            if average:
                out = out / _axis_size(axis_name)
            return out, st
        n, m = st.residual.shape
        mat = g.astype(jnp.float32).reshape(n, m) + st.residual
        p = mat @ st.q                                    # [n, r]
        p = lax.pmean(p, axis_name)
        p_hat = _orthonormalize(p)
        q = mat.T @ p_hat                                 # [m, r]
        q = lax.pmean(q, axis_name)
        approx = p_hat @ q.T                              # ≈ mean over ranks
        residual = mat - approx
        out = approx if average else approx * _axis_size(axis_name)
        return out.reshape(g.shape).astype(g.dtype), _PowerSGDLeafState(
            q=q, residual=residual
        )

    def reduce(self, grads, state, *, axis_name=AXIS_NAME, average=True):
        flat_g, treedef = jax.tree.flatten(grads)
        flat_s = treedef.flatten_up_to(state)
        outs = [
            self._reduce_leaf(g, s, axis_name, average)
            for g, s in zip(flat_g, flat_s)
        ]
        reduced = jax.tree.unflatten(treedef, [o[0] for o in outs])
        new_state = jax.tree.unflatten(treedef, [o[1] for o in outs])
        return reduced, new_state


def is_stateful_compressor(obj: Any) -> bool:
    """The protocol check DistributedOptimizer dispatches on.

    Accepts instances AND classes — the registry convention elsewhere lets
    users pass the bare class (``compression=Compression.int8``), so
    ``compression=PowerSGDCompressor`` must not crash with an unbound-method
    TypeError; DistributedOptimizer instantiates via
    :func:`as_stateful_compressor`.
    """
    return callable(getattr(obj, "init", None)) and callable(
        getattr(obj, "reduce", None)
    )


def as_stateful_compressor(obj: Any) -> Any:
    """Normalize a stateful compressor: instantiate if given the class."""
    return obj() if isinstance(obj, type) else obj
