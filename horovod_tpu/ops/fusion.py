"""Tensor Fusion — bucketed flat collectives.

TPU-native re-design of the reference's fusion buffer
(reference: horovod/common/operations.cc:788-812 lazy 64 MiB buffer alloc,
:999-1053/:1290-1369 memcpy in/out, :1916-1943 response merging ≤ threshold).

On TPU there is no hand-managed fusion buffer: we flatten same-dtype tensors,
concatenate them into buckets of at most ``HOROVOD_FUSION_THRESHOLD`` bytes,
run ONE collective per bucket, and split the result back.  Inside ``jit`` the
concat/split are free (XLA fuses them into the collective's layout
assignment), so this preserves the Horovod knob — observable bucket sizes —
while letting the compiler own the memcpys.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from horovod_tpu.utils.env import DEFAULT_FUSION_THRESHOLD_BYTES


def _nbytes(x: jax.Array) -> int:
    return int(x.size) * x.dtype.itemsize


def plan_buckets(
    tensors: Sequence,
    threshold_bytes: int | None,
    *,
    nbytes=_nbytes,
    key=lambda t: t.dtype,
) -> list[list[int]]:
    """Greedy bucketing of *consecutive* same-key items ≤ threshold.

    Mirrors the response-merging loop of the reference coordinator
    (operations.cc:1916-1943): tensors join a fused response while they share
    a fuse key (by default: dtype) and the running size stays under the
    threshold.  A tensor larger than the threshold gets its own bucket (same
    as the reference, which falls back to an unfused response).

    ``nbytes`` and ``key`` generalize the planner so the eager engine can
    bucket pending ops by (kind, op, compression, dtype) with per-rank sizes
    — one policy, both paths.
    """
    if threshold_bytes is None:
        threshold_bytes = DEFAULT_FUSION_THRESHOLD_BYTES
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_key = None
    for i, t in enumerate(tensors):
        nb = nbytes(t)
        k = key(t)
        if cur and (k != cur_key or cur_bytes + nb > threshold_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
        cur_key = k
        if threshold_bytes <= 0:  # fusion disabled: one tensor per bucket
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def fused_apply(
    tensors: list[jax.Array],
    collective: Callable[[jax.Array], jax.Array],
    *,
    threshold_bytes: int | None = None,
) -> list[jax.Array]:
    """Apply a flat-vector collective to ``tensors`` bucket-by-bucket.

    ``collective`` receives a 1-D array (the fused buffer) and must return a
    same-shaped reduced array.  Returns per-tensor results in input order.
    """
    if not tensors:
        return []
    buckets = plan_buckets(tensors, threshold_bytes)
    out: list[jax.Array | None] = [None] * len(tensors)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            t = tensors[i]
            out[i] = collective(t.reshape(-1)).reshape(t.shape)
            continue
        flats = [tensors[i].reshape(-1) for i in bucket]
        fused = jnp.concatenate(flats)
        reduced = collective(fused)
        offset = 0
        for i in bucket:
            t = tensors[i]
            out[i] = lax_slice(reduced, offset, t.size).reshape(t.shape)
            offset += t.size
    return out  # type: ignore[return-value]


def lax_slice(x: jax.Array, start: int, length: int) -> jax.Array:
    """Static slice helper (keeps shapes static under jit)."""
    return jax.lax.slice(x, (start,), (start + length,))
