"""The eager engine: async named-tensor collectives with fusion cycles.

TPU-native re-design of the reference's background coordination engine
(reference: horovod/common/operations.cc — ``BackgroundThreadLoop``
:1493-1764, ``RunLoopOnce`` :1795-2007, ``PerformOperation`` :734-1420).

What the reference engine does, and where it went on TPU:

* **Negotiation** (rank-0 gathers requests, matches readiness): exists
  because each MPI process schedules ops in nondeterministic order.  Under a
  single JAX controller, one Python thread observes *every* enqueue, so
  readiness matching is a queue.  In multi-controller jobs the user program
  is identical on every host, so op *order* agrees, but flush *timing* does
  not — therefore fusion grouping there is restricted to caller-delimited
  groups (see ``_fuse_key``), which are identical across hosts by
  construction.  The queue-until-cycle behaviour (and its observability via
  the Timeline NEGOTIATE phase) is retained.
* **Tensor fusion** (memcpy into a 64 MiB buffer, one collective): becomes
  same-dtype bucketing into ONE concatenated psum per bucket, compiled by
  XLA (see :mod:`horovod_tpu.ops.fusion`); ``HOROVOD_FUSION_THRESHOLD`` and
  ``HOROVOD_CYCLE_TIME`` keep their meaning.
* **Execution** (NCCL/MPI calls on a private stream): becomes dispatch of a
  cached jitted ``shard_map`` program; XLA owns streams, buffers and the ICI
  wire.  Async handles map onto JAX's async dispatch — a dispatched op IS a
  future.
* **Stall check** (operations.cc:1424-1470): a watchdog thread warns about
  tensors enqueued but never synchronized.

Eager tensors use the **rank-major** representation (see
:mod:`horovod_tpu.basics`): a logical per-rank tensor of shape ``S`` is one
``jax.Array`` of shape ``[size, *S]`` sharded over axis 0.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import sys
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu import basics, metrics as metrics_mod
from horovod_tpu import timeline as timeline_mod
from horovod_tpu.basics import AXIS_NAME, HorovodInternalError
from horovod_tpu.ops import collective_ops
from horovod_tpu.ops.collective_ops import Average, Sum, _ReduceOp
from horovod_tpu.ops.compression import Compression, TopKCompressor
from horovod_tpu.ops.handle_manager import HandleManager


@dataclasses.dataclass
class _PendingOp:
    kind: str                      # 'allreduce' | 'allgather' | 'broadcast' | 'sparse'
    handle: int
    tensor: jax.Array              # rank-major stacked input
    name: str
    op: _ReduceOp = Sum
    compression: Any = Compression.none
    root_rank: int = 0
    sizes: tuple[int, ...] | None = None   # ragged allgather per-rank dim-0 sizes
    topk: TopKCompressor | None = None
    group_id: int | None = None            # caller-delimited fusion group
    process_set: Any = None                # ProcessSet restricting the op
    no_fuse: bool = False                  # never share a fusion bucket
    # May a JOINED rank satisfy this op with identity (zero) inputs?
    # True for ordinary data allreduces (hvd.join semantics); False for
    # rendezvous ops like barrier, whose whole point is that every rank
    # actually arrives.
    join_identity: bool = True
    enqueued_at: float = 0.0


def _per_rank_nbytes(stacked: jax.Array) -> int:
    n = stacked.shape[0]
    return (int(stacked.size) // max(n, 1)) * stacked.dtype.itemsize


def _op_end_args(p: _PendingOp) -> dict:
    """dtype/per-rank shape for an op END event (reference
    timeline.cc:170-188 attaches them via TensorShape::DebugString), making
    each trace track diagnosable without cross-referencing code."""
    return {"dtype": str(p.tensor.dtype), "shape": list(p.tensor.shape[1:])}


class EagerEngine:
    """Background engine: queue → cycle tick → fuse → dispatch.

    One instance per :func:`horovod_tpu.init`; created lazily on first eager
    op (the reference spawns its thread inside ``InitializeHorovodOnce``,
    operations.cc:2011-2029).
    """

    # `stats` is intentionally undeclared: it is mixed-lock by design
    # (incremented under whichever lock the touching path already
    # holds — see the comment above its assignment).
    _GUARDED_BY_LOCK = {
        "_lock": ("_queue", "_join_active", "_join_result"),
        "_flush_lock": ("_submitted", "_dispatch_cache"),
    }
    # These run entirely under _flush_lock taken by flush()'s caller
    # chain; they contain no `with` of their own.
    _LOCK_HOLDER_METHODS = {
        "_flush_lock": ("_flush_via_controller", "_allreduce_group_fn",
                        "_dispatch_allreduce_group", "_dispatch_single"),
    }

    def __init__(self, mesh, cfg, timeline=None):
        self.mesh = mesh
        self.config = cfg
        self.handles = HandleManager()
        self.timeline = timeline
        self._axis: Any = AXIS_NAME
        if cfg.hierarchical_allreduce:
            # HOROVOD_HIERARCHICAL_ALLREDUCE: dispatch over a 2-D
            # (dcn, ici) mesh so XLA nests the reduction — fast ICI within
            # the local group, DCN across groups (the reference's
            # ReduceScatter→cross-MPI→AllGather pipeline,
            # operations.cc:1070-1223, expressed as mesh structure).
            local = cfg.hierarchy_local_size or jax.local_device_count()
            total = int(mesh.devices.size)
            if local > 1 and total % local == 0 and total // local > 1:
                from jax.sharding import Mesh

                self.mesh = Mesh(
                    mesh.devices.reshape(total // local, local),
                    ("dcn", "ici"),
                )
                self._axis = ("dcn", "ici")
            else:
                print(
                    "WARNING: HOROVOD_HIERARCHICAL_ALLREDUCE=1 ignored: "
                    f"world of {total} devices does not factor into "
                    f"(cross, local={local}) groups with both extents > 1; "
                    "dispatching over the flat 1-D mesh.  Set "
                    "HOROVOD_TPU_HIERARCHY_LOCAL_SIZE to a divisor of the "
                    "world size to choose the inner extent.",
                    file=sys.stderr,
                )
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._queue: list[_PendingOp] = []
        self._dispatch_cache: dict[tuple, Any] = {}
        # CPU-simulation only (same rationale as make_train_step's
        # throttle): XLA CPU collectives are matched by arrival order on
        # shared in-process/Gloo transport, so multiple collective launches
        # in flight can execute in different orders on different ranks and
        # pair mismatched messages ("received data size doesn't match").
        # Blocking per dispatch caps in-flight depth at 1; TPU's ordered
        # stream needs no throttle and keeps the async pipeline.
        # HOROVOD_TPU_SERIALIZE_DISPATCH overrides: "off" tests the
        # TPU-production pipelined path on the single-process virtual mesh
        # (one controller ⇒ one launch covers all ranks, so CPU arrival
        # order cannot diverge); "on" forces depth-1 on any backend.
        if cfg.serialize_dispatch == "on":
            self._serialize_dispatch = True
        elif cfg.serialize_dispatch == "off":
            self._serialize_dispatch = False
        else:
            self._serialize_dispatch = jax.default_backend() == "cpu"
        self._shutdown = threading.Event()
        self._tick = threading.Event()
        self.controller = self._maybe_native_controller(cfg)
        if self.controller is not None and self.timeline is not None:
            # Per-rank NEGOTIATE ticks on rank 0's timeline
            # (reference timeline.cc:98-132); drained after every tick.
            self.controller.enable_tick_trace()
        self._submitted: dict[str, _PendingOp] = {}
        # hvd.join state: while active, batches with names this rank never
        # submitted are filled with zero phantoms (_join_fill); the
        # all-joined response's last rank lands in _join_result.
        self._join_active = False
        self._join_result: int | None = None
        self.autotuner = None
        if cfg.autotune:
            if self.controller is not None:
                # Control-plane autotune: rank 0 OWNS the tuner (it owns
                # batching — BuildBatches runs only there), and every move
                # is installed into the native controller, which applies it
                # to the next tick's bucketing and piggybacks the values on
                # the response so all ranks observe the move in the same
                # tick (reference-shaped: rank-0 tunes, renegotiates
                # through the control plane).
                if jax.process_index() == 0:
                    from horovod_tpu.autotune import Autotuner

                    self.autotuner = Autotuner(
                        cfg,
                        warmup_samples=cfg.autotune_warmup_samples,
                        window_flushes=cfg.autotune_steady_state_samples,
                        log_path=cfg.autotune_log,
                        on_move=self.controller.set_tuned,
                    )
                    # No init-time SetTuned: the controller already holds
                    # the construction threshold, and pre-seeding would mark
                    # untouched defaults as "tuned", silently overriding any
                    # per-rank env differences before the first real move.
            elif jax.process_count() > 1:
                # Multi-controller WITHOUT the native controller: per-host
                # tuners scored on host-local noise would move to different
                # thresholds at different times, split the same group into
                # different buckets per host, and deadlock the
                # differently-fused collectives (see _fuse_key).
                print(
                    "WARNING: HOROVOD_AUTOTUNE=1 ignored: multi-host "
                    "autotuning requires the native controller "
                    "(HOROVOD_TPU_NATIVE_CONTROLLER=on), where rank 0 "
                    "tunes and renegotiates the threshold through the "
                    "control plane; independent per-host tuning would "
                    "diverge bucket plans across hosts.",
                    file=sys.stderr,
                )
            else:
                from horovod_tpu.autotune import Autotuner

                self.autotuner = Autotuner(
                    cfg,
                    warmup_samples=cfg.autotune_warmup_samples,
                    window_flushes=cfg.autotune_steady_state_samples,
                    log_path=cfg.autotune_log,
                )
        # Observability counters (hvd.engine_stats()): updated under the
        # engine's own locks on their paths (enqueue under _lock, dispatch
        # under _flush_lock); reads are snapshots, not a barrier.  Must
        # exist before the cycle thread starts flushing.  Every key is
        # pre-seeded so the key set never grows after __init__ — an
        # unlocked dict() snapshot in engine_stats() would otherwise race
        # a cycle-thread first-insertion and can raise "dictionary changed
        # size during iteration".
        self.stats: dict[str, int] = collections.Counter({
            "ops_enqueued": 0, "batches_dispatched": 0, "tensors_fused": 0,
            "allreduce_bytes": 0, "errors": 0, "stall_warnings": 0,
        })
        # Last-N negotiate waits (enqueue → dispatch) for the straggler
        # detector's rolling window; deque appends are atomic, so the
        # flush thread writes and engine_stats() snapshots lock-free.
        self.recent_negotiate_s: collections.deque[float] = (
            collections.deque(maxlen=256))
        self._cycle_thread = threading.Thread(
            target=self._cycle_loop, name="horovod_tpu-engine", daemon=True
        )
        self._cycle_thread.start()
        self._stall_thread: threading.Thread | None = None
        if cfg.stall_check_enabled:
            self._stall_thread = threading.Thread(
                target=self._stall_loop, name="horovod_tpu-stall-check", daemon=True
            )
            self._stall_thread.start()

    def _mark_error(self, handle: int, err: Exception) -> None:
        """Every handle failure goes through here so ``stats["errors"]``
        counts controller-path rejections (duplicate names, negotiation
        errors, shutdown orphans) the same as dispatch failures."""
        self.handles.mark_error(handle, err)
        self.stats["errors"] += 1

    def _maybe_native_controller(self, cfg):
        """Bring up the native coordination engine (native/src/controller.cc)
        when configured.  ``auto`` → multi-controller jobs only (where true
        negotiation is required for cross-host agreement on op order and
        fusion — the job the reference's C++ coordinator does,
        operations.cc:1795-2007); ``on`` forces it (tests / soak);
        ``off``/unavailable → pure-Python coordination."""
        mode = (cfg.native_controller or "auto").lower()
        if mode in ("off", "0", "false", "no"):
            return None
        nproc = jax.process_count()
        if mode == "auto" and nproc == 1:
            return None
        from horovod_tpu import native

        if not native.available():
            if mode != "auto":
                raise RuntimeError(
                    "HOROVOD_TPU_NATIVE_CONTROLLER=on but libhvdtpu.so "
                    "could not be built/loaded"
                )
            return None
        spec = cfg.controller_transport
        if spec is None:
            if nproc > 1:
                if mode != "auto":
                    raise RuntimeError(
                        "HOROVOD_TPU_NATIVE_CONTROLLER=on on a multi-host "
                        "job requires HOROVOD_TPU_CONTROLLER_TRANSPORT "
                        "(e.g. tcp:<rank0-host>:<port>)"
                    )
                # auto multi-host with no transport configured: fall back to
                # Python coordination (caller-delimited fusion groups only).
                print(
                    "WARNING: horovod_tpu eager collectives on a multi-host "
                    "job without HOROVOD_TPU_CONTROLLER_TRANSPORT: falling "
                    "back to Python coordination.  Only caller-delimited "
                    "groups (grouped_allreduce_eager) will fuse, and "
                    "cross-host agreement relies on identical program "
                    "order; set HOROVOD_TPU_CONTROLLER_TRANSPORT="
                    "tcp:<rank0-host>:<port> to enable true negotiation.",
                    file=sys.stderr,
                )
                return None
            import os as _os

            spec = f"local:engine-{_os.getpid()}"
        return native.NativeController(
            rank=jax.process_index(),
            size=nproc,
            transport_spec=spec,
            fusion_threshold_bytes=cfg.fusion_threshold_bytes,
            stall_warning_s=cfg.stall_warning_time_s,
        )

    # ------------------------------------------------------------------ queue

    def enqueue(self, pending: _PendingOp) -> int:
        """Analogue of EnqueueTensorAllreduce/Allgather/Broadcast
        (reference operations.cc:2099-2215): push into the shared queue under
        the table mutex; the cycle thread picks it up."""
        self.enqueue_many([pending])
        return pending.handle

    def enqueue_many(self, pendings: list[_PendingOp]) -> None:
        """Enqueue a caller-delimited group ATOMICALLY (one lock
        acquisition), so no cycle-thread flush can observe a partial group.

        This is what makes grouped fusion deterministic: with the whole
        group entering the queue at once and ``_fuse_key`` isolating it by
        ``group_id``, every flush sees the same bucket composition for the
        same call — and therefore the same jitted-program signatures.
        Per-op enqueue would let the tick cut the group at a wall-clock-
        dependent point, compiling a fresh program arity per cut (compile
        churn measured at ~240 ms per novel signature on the CPU sim).
        """
        now = time.monotonic()
        for p in pendings:
            p.enqueued_at = now
            if self.timeline:
                self.timeline.start(
                    p.name, timeline_mod.NEGOTIATE + "_" + p.kind.upper()
                )
                if self.controller is None:
                    # Single controller: one thread observes every enqueue,
                    # so all ranks' readiness arrives at once — one tick
                    # covers the reference's per-rank tick events
                    # (timeline.cc:98-132).
                    self.timeline.instant(p.name, "NEGOTIATE_TICK_ALL")
        with self._lock:
            if self._shutdown.is_set():
                raise HorovodInternalError(
                    "horovod_tpu engine has been shut down")
            self._queue.extend(pendings)
            self.stats["ops_enqueued"] += len(pendings)

    def _fuse_key(self, p: _PendingOp):
        """Fusability key for :func:`fusion.plan_buckets` — the eager
        analogue of the reference's same-type/same-device merge predicate
        (operations.cc:1916-1943).

        In multi-controller jobs, fusion decided by host-local flush timing
        would let different hosts dispatch differently-fused collectives and
        deadlock; there, only *caller-delimited* groups (grouped_allreduce's
        ``group_id``, identical across hosts because the user program is)
        may fuse.  Single-controller keeps timing-based fusion — one thread
        observes every enqueue, so any grouping is consistent.
        """
        if p.kind != "allreduce":
            return ("solo", p.handle)
        if p.op is collective_ops.Adasum or p.no_fuse:
            # Adasum's inner products are per-tensor; no_fuse callers
            # (e.g. int8 error feedback, whose residual must reproduce the
            # wire's exact block quantization) opt out explicitly.
            return ("solo", p.handle)
        ps = p.process_set.ranks if p.process_set is not None else None
        base = ("ar", p.op.name, p.compression, str(p.tensor.dtype), ps)
        if p.group_id is not None:
            # Caller-delimited groups are isolated whenever fusion is
            # planned HERE (single host, or multi-host without the native
            # controller — the controller path negotiates its own merge,
            # see _controller_group): members enter the queue atomically
            # (enqueue_many), so bucket composition — and with it the
            # jitted dispatch-program signature — is identical on every
            # call instead of varying with where the cycle tick happened
            # to cut the queue.
            return base + (("grp", p.group_id),)
        if jax.process_count() > 1:
            return base + (("solo", p.handle),)
        return base

    def flush(self) -> None:
        """Drain the queue now: group, fuse, dispatch.

        The analogue of one ``RunLoopOnce`` tick (operations.cc:1795-2007).
        With the native controller, requests are negotiated (gather → match
        → fuse → bcast, native/src/controller.cc) and dispatch follows the
        returned batch order; without it, negotiation is a no-op under the
        single controller (see module docstring) and fusion is planned
        locally.  Serialized under ``_flush_lock`` so concurrent callers
        (cycle thread, poll, synchronize) cannot interleave dispatch order.
        """
        from horovod_tpu.ops import fusion

        tune_sample = None
        with self._flush_lock:
            with self._lock:
                batch, self._queue = self._queue, []
            if self.controller is not None:
                # Controller path: the returned sample (rank 0 with
                # autotune only) is its dispatched allreduce traffic.
                tune_sample = self._flush_via_controller(batch)
            elif batch:
                for p in batch:
                    self._end_negotiate(p)
                buckets = fusion.plan_buckets(
                    batch,
                    self.config.fusion_threshold_bytes,
                    nbytes=lambda p: _per_rank_nbytes(p.tensor),
                    key=self._fuse_key,
                )
                ar_bytes, sample_out = 0, None
                for bucket in buckets:
                    group = [batch[i] for i in bucket]
                    if group[0].kind == "allreduce":
                        out, nb = self._dispatch_allreduce_group(group)
                        if out is not None:
                            ar_bytes += nb
                            sample_out = out
                    else:
                        assert len(group) == 1
                        self._dispatch_single(group[0])
                if self.autotuner is not None and ar_bytes:
                    tune_sample = (ar_bytes, sample_out)
        # Score OUTSIDE the flush lock: closing a window blocks on device
        # completion of the probe, and holding the lock through that would
        # stall every concurrent synchronize()/poll() flush.
        if tune_sample is not None and self.autotuner is not None:
            self.autotuner.observe(*tune_sample)

    _KIND_CODES = {"allreduce": 0, "allgather": 1, "broadcast": 2,
                   "sparse": 3, "alltoall": 4, "reducescatter": 5}

    def _controller_group(self, p: _PendingOp) -> int:
        """Encode fusability (reduce op, compression) into the controller's
        int64 ``group`` so negotiation never merges requests that need
        different compiled programs.

        The id must be a pure function of the key — NOT encounter order,
        which differs across ranks when flush timing differs, and would let
        the controller fuse a Sum with a Min (dispatched with group[0]'s op
        → silently wrong numerics).

        Caller-delimited group ids ARE included: cross-group merging would
        be *correct* (the batch order is globally agreed), but it makes
        bucket composition depend on what other traffic shared the
        negotiation tick — and under XLA every novel composition is a
        fresh compiled dispatch program (docs/tensor-fusion.md
        "Determinism and compile churn").  Group ids come from a
        per-process counter, identical across ranks exactly when the user
        program is — the same contract grouped fusion already relies on in
        the controller-less multi-host mode.  A divergent program cannot
        deadlock on it: the first-arriving rank's token wins at the
        coordinator and the batch it broadcasts is what every rank
        dispatches."""
        if p.kind != "allreduce":
            return -1
        comp = getattr(p.compression, "__name__", None) or type(
            p.compression
        ).__name__
        ps = p.process_set.ranks if p.process_set is not None else ()
        token = f"{p.op.name}:{comp}:{ps}".encode()
        if p.group_id is not None:
            token += b":grp:" + str(p.group_id).encode()
        if p.no_fuse:
            # Only the same-named request from the other ranks may join
            # this batch — names are identical across ranks, so the batch
            # stays exactly one tensor everywhere.
            token += b":" + p.name.encode()
        import hashlib

        return int.from_bytes(hashlib.sha1(token).digest()[:7], "big")

    @staticmethod
    def _op_code(p: _PendingOp) -> int:
        """Dispatch-program code for join support (types.h OpCode): a
        joined rank can fabricate identity inputs only for the plain
        Sum/Average allreduce program — everything else is kOpOther and
        the controller errors it if it can only complete via joins."""
        from horovod_tpu import native

        if (p.kind == "allreduce" and p.process_set is None
                and p.compression is Compression.none
                and p.join_identity):
            if p.op is Sum:
                return native.OP_PLAIN_SUM
            if p.op is Average:
                return native.OP_PLAIN_AVERAGE
        return native.OP_OTHER

    def _join_fill(self, b, ops: list[_PendingOp]) -> list[_PendingOp] | None:
        """Fill a batch this JOINED rank only partially (or never)
        submitted: phantom ops with identity (zero) inputs stand in for
        the missing names, so this rank launches the SAME compiled
        collective as its active peers — the XLA collective is global
        across processes, and a joined rank that skipped the launch would
        hang the gang (the join op of Horovod ≥0.21 feeds zero tensors the
        same way).  Returns None when the batch is not join-eligible
        (then the caller's silent-skip fallback applies)."""
        from horovod_tpu import native

        if (not self._join_active or b.kind != native.KIND_ALLREDUCE
                or b.op_code not in (native.OP_PLAIN_SUM,
                                     native.OP_PLAIN_AVERAGE)):
            return None
        import numpy as _np

        dtype = _np.dtype(native.DTYPE_NAMES.get(b.dtype, "float32"))
        op = (Average if b.op_code == native.OP_PLAIN_AVERAGE else Sum)
        n = self.mesh.devices.size
        by_name = {p.name: p for p in ops}
        return [
            by_name.get(name) or _PendingOp(
                kind="allreduce", handle=-1,
                tensor=jnp.zeros((n, *shape), dtype=dtype), name=name, op=op,
            )
            for name, shape in zip(b.names, b.shapes)
        ]

    def _flush_via_controller(self, batch: list[_PendingOp]):
        """Submit new requests, run one negotiation tick, dispatch the
        globally-agreed batches (names → this process's pending ops).

        Returns ``(allreduce_bytes, sample_output)`` when this rank runs
        the autotuner (rank 0) and the tick dispatched allreduce traffic;
        None otherwise."""
        for p in batch:
            if p.name in self._submitted:
                # The reference rejects duplicate in-flight names at enqueue
                # (operations.cc:2124-2134).
                self._end_negotiate(p)
                self._mark_error(
                    p.handle,
                    RuntimeError(f"Duplicate tensor name in flight: {p.name}"),
                )
                continue
            try:
                self.controller.submit(
                    self._KIND_CODES[p.kind],
                    str(p.tensor.dtype),
                    p.name,
                    tuple(p.tensor.shape[1:]),
                    root_rank=p.root_rank,
                    group=self._controller_group(p),
                    op_code=self._op_code(p),
                )
            except Exception as e:
                # Per-op containment, like the non-controller dispatch path:
                # a rejected request fails ITS handle, not the whole flush.
                self._end_negotiate(p)
                self._mark_error(p.handle, e)
                continue
            self._submitted[p.name] = p
        try:
            # hvdlint: disable=HVD008 -- negotiated dispatch IS the flush lock's critical section; serializing it is the lock's purpose (see flush docstring)
            bl = self.controller.tick()
        except Exception as e:
            # A broken control plane strands every outstanding op; fail
            # their handles so waiters unblock instead of hanging.  Typed
            # HorovodInternalError (environmental, not a caller mistake)
            # so elastic.run can recover by reinit + replay.
            err = HorovodInternalError(f"control plane failed: {e}")
            err.__cause__ = e
            for p in self._submitted.values():
                self._end_negotiate(p)
                self._mark_error(p.handle, err)
            self._submitted.clear()
            raise err
        if self.timeline:
            for tname, trank in self.controller.drain_ticks():
                self.timeline.instant(tname, f"NEGOTIATE_TICK_r{trank}")
        # Control-plane autotune: apply rank-0's tuned knobs, piggybacked on
        # every response, so the whole gang's config moves in the same tick
        # (bucketing itself is already rank-0-owned via BuildBatches).  The
        # tuner OWNER skips the apply: its tuner writes config directly in
        # _move_to, and a response built just before a move landed would
        # briefly roll its config back.
        if self.autotuner is None:
            if bl.tuned_threshold_bytes is not None:
                self.config.fusion_threshold_bytes = bl.tuned_threshold_bytes
            if bl.tuned_cycle_ms is not None:
                self.config.cycle_time_ms = bl.tuned_cycle_ms
        if bl.last_joined >= 0:
            with self._lock:
                self._join_result = bl.last_joined
        ar_bytes, sample_out = 0, None
        for b in bl.batches:
            ops = [
                self._submitted.pop(n) for n in b.names if n in self._submitted
            ]
            if len(ops) != len(b.names) and not b.error:
                full = self._join_fill(b, ops)
                if full is not None:
                    for p in ops:
                        self._end_negotiate(p)
                    out, nb = self._dispatch_allreduce_group(full)
                    if out is not None and ops:
                        ar_bytes += nb
                        sample_out = out
                    continue
            if not ops:
                continue
            for p in ops:
                self._end_negotiate(p)
            if b.error:
                err = RuntimeError(b.error)
                for p in ops:
                    self._mark_error(p.handle, err)
            elif ops[0].kind == "allreduce":
                out, nb = self._dispatch_allreduce_group(ops)
                if out is not None:
                    ar_bytes += nb
                    sample_out = out
            else:
                for p in ops:
                    self._dispatch_single(p)
        if bl.shutdown:
            # Orphaned ops (submitted but never matched before the shutdown
            # response) must error, not hang their waiters — parity with the
            # reference's SHUT_DOWN_ERROR callbacks (operations.cc:278-283).
            err = HorovodInternalError(
                "horovod_tpu has been shut down; collective was not "
                "completed by all ranks"
            )
            for p in self._submitted.values():
                self._end_negotiate(p)
                self._mark_error(p.handle, err)
            self._submitted.clear()
            self._shutdown.set()
        if self.autotuner is not None and ar_bytes:
            return (ar_bytes, sample_out)
        return None

    def _end_negotiate(self, p: _PendingOp) -> None:
        # Queue-time histogram: enqueue → the flush deciding to run the
        # op, the same span the timeline's NEGOTIATE phase draws — but
        # scrapeable with no timeline attached.
        if p.enqueued_at:
            wait = time.monotonic() - p.enqueued_at
            metrics_mod.DEFAULT.histogram("hvd.negotiate_s").observe(wait)
            self.recent_negotiate_s.append(wait)
        if self.timeline:
            self.timeline.end(
                p.name, timeline_mod.NEGOTIATE + "_" + p.kind.upper()
            )

    def join(self) -> int:
        """Declare this rank out of data (the ``hvd.join()`` API Horovod
        grew in 0.21 for uneven datasets): block until EVERY rank has
        joined, meanwhile participating in the gang's remaining plain
        Sum/Average allreduces with identity (zero) inputs so active ranks
        never stall.  Returns the last rank to join — a root guaranteed to
        have processed all its data.

        Needs the native controller (multi-process gangs).  In a
        single-controller world every rank is driven by this process, so
        all "join" simultaneously: returns ``size - 1`` immediately.
        """
        if self.controller is None:
            if jax.process_count() > 1:
                raise RuntimeError(
                    "hvd.join() needs the native controller "
                    "(HOROVOD_TPU_NATIVE_CONTROLLER=on + a controller "
                    "transport); Python-degraded coordination cannot "
                    "negotiate joined ranks"
                )
            self.flush()
            return self.mesh.devices.size - 1
        self.flush()                     # drain this rank's own queue first
        with self._lock:
            self._join_result = None
            self._join_active = True
        try:
            self.controller.submit_join()
            while True:
                self.flush()
                with self._lock:
                    r = self._join_result
                if r is not None:
                    return r
                if self._shutdown.is_set():
                    raise HorovodInternalError(
                        "engine shut down while waiting in hvd.join()"
                    )
                time.sleep(max(self.config.cycle_time_ms, 0.5) / 1000.0)
        finally:
            with self._lock:
                self._join_active = False
                self._join_result = None

    def _cycle_loop(self) -> None:
        """Background tick every ``HOROVOD_CYCLE_TIME`` ms
        (reference operations.cc:1795 tick + :1661-1685 knob).  The period
        is re-read every iteration: the autotuner mutates it mid-run."""
        while not self._shutdown.is_set():
            period = max(self.config.cycle_time_ms, 0.1) / 1000.0
            self._tick.wait(timeout=period)
            self._tick.clear()
            try:
                self.flush()
                tl = self.timeline
                if tl is not None and tl.mark_cycles:
                    # hvd.start_timeline(mark_cycles=True) parity: one
                    # instant per engine tick on a dedicated track.
                    tl.instant("_engine", "CYCLE_START")
            except Exception:  # pragma: no cover - defensive: keep ticking
                import traceback

                traceback.print_exc(file=sys.stderr)

    def _stall_loop(self) -> None:
        """Warn about tensors stuck in the queue — parity with
        CheckForStalledTensors (reference operations.cc:1424-1470)."""
        warn_after = self.config.stall_warning_time_s
        while not self._shutdown.is_set():
            self._shutdown.wait(timeout=min(warn_after / 4.0, 15.0))
            if self._shutdown.is_set():
                return
            now = time.monotonic()
            with self._lock:
                stalled = [
                    p.name for p in self._queue if now - p.enqueued_at > warn_after
                ]
            if self.controller is not None:
                # Rank-0's native table knows which ranks are missing
                # (reference stall message lists them, operations.cc:1455).
                report = self.controller.stall_report()
                if report:
                    stalled.append(report)
            if stalled:
                self.stats["stall_warnings"] += 1
                print(
                    "WARNING: One or more tensors were submitted to be "
                    "reduced, gathered or broadcasted by subset of ranks and "
                    f"are waiting for remainder of ranks for more than {int(warn_after)} "
                    "seconds. Stalled ops: " + ", ".join(sorted(stalled)),
                    file=sys.stderr,
                )

    def shutdown(self) -> None:
        """Coordinated shutdown: flush outstanding work, propagate the
        shutdown through the control plane, stop threads
        (reference operations.cc:1699-1729)."""
        try:
            self.flush()
            if self.controller is not None:
                # One more negotiated tick so every rank sees the shutdown
                # response (reference :1881-1884, 1906).
                self.controller.request_shutdown()
                self.flush()
        finally:
            self._shutdown.set()
            self._tick.set()
            if self._cycle_thread.is_alive():
                self._cycle_thread.join(timeout=5)
            if self._stall_thread is not None and self._stall_thread.is_alive():
                self._stall_thread.join(timeout=5)
            if self.controller is not None:
                self.controller.close()

    # --------------------------------------------------------------- dispatch

    def _shard_map(self, fn, out_specs=P()):
        # check_vma/check_rep=False: outputs of these dispatch programs
        # are replicated by construction (psum / all_gather semantics),
        # which the varying-manual-axes inference cannot always prove.
        from horovod_tpu.utils.compat import shard_map

        return jax.jit(
            shard_map(
                fn,
                mesh=self.mesh,
                in_specs=P(self._axis),
                out_specs=out_specs,
                check_vma=False,
            )
        )

    def _allreduce_group_fn(self, op: _ReduceOp, compression,
                            process_set=None) -> Any:
        """One jitted program: concat per-rank flats → ONE collective →
        split.  This is the Horovod fusion buffer, compiled
        (reference operations.cc:999-1053 memcpys become XLA layout ops)."""
        ps_key = process_set.ranks if process_set is not None else None
        key = ("ar", op.name, compression, ps_key)
        fn = self._dispatch_cache.get(key)
        if fn is None:

            def fused(xs):
                flats = [x.reshape(-1) for x in xs]
                buf = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
                red = collective_ops.allreduce(
                    buf, op=op, axis_name=self._axis, compression=compression,
                    process_set=process_set,
                )
                outs, off = [], 0
                for x in xs:
                    n = int(x.size)
                    outs.append(lax.slice(red, (off,), (off + n,)))
                    off += n
                return tuple(outs)

            # Process-set results differ per rank (non-members keep their
            # input), so they come back rank-major instead of replicated.
            fn = self._shard_map(
                fused,
                out_specs=P(self._axis) if process_set is not None else P(),
            )
            self._dispatch_cache[key] = fn
        return fn

    def _dispatch_allreduce_group(self, group: list[_PendingOp]):
        """Dispatch one fused bucket; returns ``(last_output_or_None,
        bucket_bytes)`` — the output feeds the autotuner's completion
        probe, the per-rank payload bytes feed stats and the autotune
        sample (computed once here so the two meters cannot diverge)."""
        names = [p.name for p in group]
        nbytes = sum(_per_rank_nbytes(p.tensor) for p in group)
        # Snapshot: start_timeline() may attach a timeline while we're in
        # the try block, and emitting E events whose B never happened would
        # break the trace's B/E balance.
        tl = self.timeline
        if tl:
            for n in names:
                tl.start(n, "ALLREDUCE", {"fused_with": len(group) - 1})
                tl.start(n, timeline_mod.DISPATCH)
        try:
            ps = group[0].process_set
            fn = self._allreduce_group_fn(group[0].op, group[0].compression, ps)
            outs = fn(tuple(p.tensor.reshape(p.tensor.shape[0], -1) for p in group))
            if self._serialize_dispatch:
                jax.block_until_ready(outs)
            for p, out in zip(group, outs):
                if p.handle < 0:
                    continue  # joined-rank phantom: output discarded
                shape = p.tensor.shape if ps is not None else p.tensor.shape[1:]
                self.handles.mark_dispatched(p.handle, out.reshape(shape))
            self.stats["batches_dispatched"] += 1
            if len(group) > 1:
                self.stats["tensors_fused"] += len(group)
            self.stats["allreduce_bytes"] += nbytes
            metrics_mod.DEFAULT.counter("hvd.allreduce_bytes").inc(nbytes)
            return outs[-1], nbytes
        except Exception as e:
            for p in group:
                if p.handle >= 0:
                    self._mark_error(p.handle, e)
            return None, nbytes
        finally:
            if tl:
                for n, p in zip(names, group):
                    tl.end(n, timeline_mod.DISPATCH)
                    tl.end(n, "ALLREDUCE", _op_end_args(p))

    def _mark_single(self, p: _PendingOp, out) -> None:
        if self._serialize_dispatch:
            jax.block_until_ready(out)
        self.handles.mark_dispatched(p.handle, out)

    def _dispatch_single(self, p: _PendingOp) -> None:
        tl = self.timeline   # snapshot; see _dispatch_allreduce_group
        if tl:
            tl.start(p.name, p.kind.upper())
        try:
            if p.kind == "broadcast":
                ps = p.process_set
                ps_key = ps.ranks if ps is not None else None
                key = ("bc", int(p.root_rank), ps_key)
                fn = self._dispatch_cache.get(key)
                if fn is None:
                    root = int(p.root_rank)

                    def bc(x):
                        out = collective_ops.broadcast(
                            x[0], root, axis_name=self._axis, process_set=ps
                        )
                        # Rank-major output keeps the leading rank axis so
                        # the stacked global shape is [size, *shape].
                        return out[None] if ps is not None else out

                    # With a process set the output differs per rank
                    # (members get root's value, others keep their own), so
                    # it stays rank-major instead of collapsing to one copy.
                    fn = self._shard_map(
                        bc, out_specs=P(self._axis) if ps is not None else P()
                    )
                    self._dispatch_cache[key] = fn
                self._mark_single(p, fn(p.tensor))
            elif p.kind == "allgather":
                fn = self._dispatch_cache.get("ag")
                if fn is None:

                    def ag(x):
                        return lax.all_gather(x[0], self._axis, tiled=True)

                    fn = self._shard_map(ag)
                    self._dispatch_cache["ag"] = fn
                gathered = fn(p.tensor)  # [size * padded_d0, rest]
                if p.sizes is not None or p.process_set is not None:
                    # One slice loop covers both the ragged case (per-rank
                    # first dims) and the process-set case (member blocks
                    # only): a fixed first dim is just sizes == (pad,)*n.
                    pad = p.tensor.shape[1]
                    sizes = p.sizes or (pad,) * p.tensor.shape[0]
                    member_ranks = (
                        range(p.tensor.shape[0]) if p.process_set is None
                        else p.process_set.ranks
                    )
                    gathered = jnp.concatenate(
                        [
                            lax.slice_in_dim(
                                gathered, r * pad, r * pad + sizes[r], axis=0
                            )
                            for r in member_ranks
                        ],
                        axis=0,
                    )
                self._mark_single(p, gathered)
            elif p.kind == "alltoall":
                fn = self._dispatch_cache.get("a2a")
                if fn is None:

                    def a2a(x):
                        # Per-rank block [1, m, ...] → split row into n
                        # chunks, exchange, concat: rank r's output row is
                        # chunk r of every rank (Horovod ≥0.20 hvd.alltoall
                        # semantics, equal splits).
                        out = lax.all_to_all(
                            x[0], self._axis, split_axis=0, concat_axis=0,
                            tiled=True,
                        )
                        return out[None]

                    fn = self._shard_map(a2a, out_specs=P(self._axis))
                    self._dispatch_cache["a2a"] = fn
                self._mark_single(p, fn(p.tensor))
            elif p.kind == "reducescatter":
                key = ("rs", p.op.name)
                fn = self._dispatch_cache.get(key)
                if fn is None:
                    rs_op = p.op

                    def rs(x):
                        # Per-rank row [1, m, ...] → this rank's reduced
                        # shard [1, m/n, ...] (Horovod ≥0.21
                        # hvd.reducescatter semantics); the numerics live
                        # in collective_ops.reducescatter — the
                        # ncclReduceScatter leg of the reference's
                        # hierarchical allreduce, operations.cc:1135-1158.
                        return collective_ops.reducescatter(
                            x[0], op=rs_op, axis_name=self._axis
                        )[None]

                    fn = self._shard_map(rs, out_specs=P(self._axis))
                    self._dispatch_cache[key] = fn
                self._mark_single(p, fn(p.tensor))
            elif p.kind == "sparse":
                topk = p.topk
                key = ("sp", topk.ratio, topk.k, p.op.name)
                fn = self._dispatch_cache.get(key)
                if fn is None:
                    avg = p.op is Average

                    def sp(x):
                        return topk.sparse_allreduce(
                            x[0], average=avg, axis_name=self._axis
                        )

                    fn = self._shard_map(sp)
                    self._dispatch_cache[key] = fn
                self._mark_single(p, fn(p.tensor))
            else:  # pragma: no cover
                raise ValueError(f"unknown op kind {p.kind}")
            self.stats["batches_dispatched"] += 1
        except Exception as e:
            self._mark_error(p.handle, e)
        finally:
            if tl:
                tl.end(p.name, p.kind.upper(), _op_end_args(p))


# ---------------------------------------------------------------------------
# Module-level eager API (the reference's horovod/torch/mpi_ops.py surface).
# ---------------------------------------------------------------------------

_group_counter = itertools.count()
_name_counter = threading.Lock()
_name_seq = 0


def _auto_name(prefix: str) -> str:
    global _name_seq
    with _name_counter:
        _name_seq += 1
        return f"{prefix}.noname.{_name_seq}"


def _engine() -> EagerEngine:
    st = basics._require_init()
    with st.lock:
        if st.engine is None:
            if st.timeline is None:
                # A start_timeline() call before the first eager op may
                # already have installed one — never clobber it with the
                # (possibly unset) env config.
                st.timeline = timeline_mod.maybe_create(
                    st.config.timeline_file
                )
            st.engine = EagerEngine(st.mesh, st.config, st.timeline)
        return st.engine


def _as_rank_major(tensor, kind: str) -> jax.Array:
    t = jnp.asarray(tensor)
    n = basics.size()
    if t.ndim == 0 or t.shape[0] != n:
        raise ValueError(
            f"eager {kind} expects a rank-major array of shape [size={n}, ...]; "
            f"got shape {t.shape}.  Build one with horovod_tpu.from_per_rank / "
            "per_rank, or use a replicated value with hvd.broadcast semantics."
        )
    if not isinstance(t, jax.Array) or t.sharding != basics.rank_sharding():
        t = jax.device_put(t, basics.rank_sharding())
    return t


def allreduce_async(
    tensor,
    average: bool | None = None,
    name: str | None = None,
    *,
    op: _ReduceOp = Sum,
    compression=Compression.none,
    group_id: int | None = None,
    process_set=None,
    no_fuse: bool = False,
    join_identity: bool = True,
) -> int:
    """Async all-reduce of a rank-major tensor; returns a handle
    (reference horovod/torch/mpi_ops.py:156-176).  ``process_set``
    restricts the reduction to member ranks; non-member rows pass through
    unchanged (Horovod ≥0.22 API).  ``no_fuse=True`` keeps this op out of
    every fusion bucket (for callers whose local math must reproduce the
    wire's per-tensor form exactly, e.g. int8 error feedback)."""
    eng, pending = _prepare_allreduce(
        tensor, average, name, op=op, compression=compression,
        group_id=group_id, process_set=process_set, no_fuse=no_fuse,
        join_identity=join_identity,
    )
    eng.enqueue(pending)
    return pending.handle


def _prepare_allreduce(tensor, average, name, *, op, compression, group_id,
                       process_set, no_fuse, join_identity=True):
    """Build (engine, ready-to-enqueue _PendingOp) — shared by the per-op
    async path and the atomic grouped path."""
    if average is not None:
        op = Average if average else Sum
    eng = _engine()
    t = _as_rank_major(tensor, "allreduce")
    name = name or _auto_name("allreduce")
    h = eng.handles.allocate(name)
    return eng, _PendingOp(
        kind="allreduce",
        handle=h,
        tensor=t,
        name=name,
        op=op,
        compression=compression,
        group_id=group_id,
        process_set=process_set,
        no_fuse=no_fuse,
        join_identity=join_identity,
    )


def allreduce(tensor, average: bool | None = None, name: str | None = None,
              *, op: _ReduceOp = Sum, compression=Compression.none,
              process_set=None):
    """Blocking all-reduce (reference horovod/torch/mpi_ops.py:60-109).
    Returns the reduced tensor, fully replicated over the mesh.  With a
    ``process_set`` the result differs per rank (non-members keep their
    input), so it comes back rank-major ``[size, ...]``."""
    return synchronize(
        allreduce_async(tensor, average, name, op=op, compression=compression,
                        process_set=process_set)
    )


def sparse_allreduce_async(
    tensor, name: str | None = None, *, average: bool = False,
    ratio: float = 0.01, k: int | None = None,
) -> int:
    """Fork-parity top-k sparse allreduce (reference
    horovod/torch/__init__.py:46-83), compiled: top_k → all_gather →
    scatter-add in one program."""
    eng = _engine()
    t = _as_rank_major(tensor, "sparse_allreduce")
    name = name or _auto_name("sparse_allreduce")
    h = eng.handles.allocate(name)
    eng.enqueue(
        _PendingOp(
            kind="sparse",
            handle=h,
            tensor=t,
            name=name,
            op=Average if average else Sum,
            topk=TopKCompressor(ratio=ratio, k=k),
        )
    )
    return h


def sparse_allreduce(tensor, name: str | None = None, *, average: bool = False,
                     ratio: float = 0.01, k: int | None = None):
    return synchronize(
        sparse_allreduce_async(tensor, name, average=average, ratio=ratio, k=k)
    )


def allgather_async(tensors, name: str | None = None, *,
                    process_set=None, sizes=None) -> int:
    """Async allgather; ``tensors`` is rank-major or a list of per-rank
    tensors whose first dims may differ (reference allgather-with-unequal-
    first-dims, operations.cc:841-901 — size negotiation happens host-side
    here since the controller sees every rank's shape).

    ``sizes``: for RANK-MAJOR input ``[size, pad, ...]``, the per-rank
    true first dims (each ≤ pad) from
    :func:`negotiate_gather_sizes` — the engine then returns the ragged
    concatenation directly (one slicing implementation for the list,
    torch, and keras frontends).  The list form negotiates its own.

    Cost note: the ragged slice/concat are device ops whose compiled
    forms cache per (pad, sizes) composition, so a hot loop whose
    per-rank sizes VARY every step pays a small fresh compile each step
    (expensive over a remote-compile tunnel).  That trade favors the
    actual ragged users — object/metric collectives, negotiated
    per call anyway; a per-step ragged hot loop should pad to a fixed
    shape instead (docs/tensor-fusion.md "Determinism and compile
    churn")."""
    eng = _engine()
    if isinstance(tensors, (list, tuple)):
        if sizes is not None:
            raise ValueError(
                "sizes= applies to rank-major input only (the per-rank "
                "list form derives sizes from the tensors themselves)"
            )
        n = basics.size()
        if len(tensors) != n:
            raise ValueError(f"expected {n} per-rank tensors, got {len(tensors)}")
        ts = [jnp.asarray(t) for t in tensors]
        rests = {t.shape[1:] for t in ts}
        if len(rests) > 1:
            raise ValueError(
                "allgather: per-rank tensors must agree on all dims except "
                f"dim 0; got trailing shapes {sorted(map(str, rests))}"
            )
        dtypes = {t.dtype for t in ts}
        if len(dtypes) > 1:
            raise ValueError(
                f"allgather: per-rank tensors must share a dtype; got {dtypes}"
            )
        sizes = tuple(int(t.shape[0]) for t in ts)
        pad = max(sizes)
        padded = [
            jnp.pad(t, [(0, pad - t.shape[0])] + [(0, 0)] * (t.ndim - 1))
            for t in ts
        ]
        t = jax.device_put(jnp.stack(padded), basics.rank_sharding())
        if len(set(sizes)) == 1:
            sizes = None
    else:
        t = _as_rank_major(tensors, "allgather")
        if sizes is not None:
            sizes = tuple(int(s) for s in sizes)
            if t.ndim < 2:
                raise ValueError(
                    "ragged allgather needs rank-major [size, pad, ...] "
                    f"input; got shape {t.shape}"
                )
            if len(sizes) != t.shape[0]:
                raise ValueError(
                    f"sizes must have one entry per rank ({t.shape[0]}); "
                    f"got {len(sizes)}"
                )
            pad = int(t.shape[1])
            if any(not 0 <= s <= pad for s in sizes):
                raise ValueError(
                    f"sizes must lie in [0, padded dim {pad}]; got {sizes}"
                )
            if len(set(sizes)) == 1 and sizes[0] == pad:
                sizes = None    # not actually ragged: plain gather
    if process_set is not None and process_set.ranks[-1] >= basics.size():
        raise ValueError(
            f"process set {process_set.ranks} exceeds world size "
            f"{basics.size()}"
        )
    name = name or _auto_name("allgather")
    h = eng.handles.allocate(name)
    eng.enqueue(
        _PendingOp(
            kind="allgather",
            handle=h,
            tensor=t,
            name=name,
            sizes=sizes,
            process_set=process_set,
        )
    )
    return h


def allgather(tensors, name: str | None = None, *, process_set=None,
              sizes=None):
    """Blocking allgather.  With a ``process_set``, the result is the
    concatenation of MEMBER ranks' slices only (set order)."""
    return synchronize(allgather_async(tensors, name,
                                       process_set=process_set,
                                       sizes=sizes))


MAX_GATHER_NDIM = 8


def negotiate_gather_sizes(shape: Sequence[int], dtype_str: str,
                           name: str | None = None) -> list[int]:
    """Exchange (ndim, dtype, shape) across ranks THROUGH the engine — not
    an out-of-band host collective, so it serializes with every queued
    engine op (no cross-host op-order divergence) — and return the
    per-rank dim-0 sizes for a ragged allgather (the reference's
    unequal-first-dim negotiation, operations.cc:841-901).

    Frontend-agnostic: callers pass the local shape and a dtype STRING
    (consistent within a frontend: every rank runs the same one).  Raises
    the same clean errors for ndim/dtype/trailing-dim mismatch on every
    rank.  Used by the torch and keras frontends."""
    return negotiate_gather_sizes_many([shape], [dtype_str], name)[0]


def negotiate_gather_sizes_many(
    shapes: Sequence[Sequence[int]], dtype_strs: Sequence[str],
    name: str | None = None,
) -> list[list[int]]:
    """Batched :func:`negotiate_gather_sizes`: K members' digests ride ONE
    engine allgather (one control-plane round-trip however many tensors a
    grouped call carries), validated member-by-member with the same
    symmetric errors.

    The digest is prefixed by a member-count header that goes over its
    OWN fixed-width exchange first: the wide digest's wire width is a
    function of K, so ranks disagreeing on K (mismatched grouped-call
    lists) would hit an opaque engine shape error — or deadlock — before
    any validation could run.  The [1] header cannot mismatch in shape,
    so a K disagreement raises the same "group member count differs"
    error on every rank with both exchanges fully drained (no engine
    desync for subsequent ops).  Cost: one extra tiny control round-trip
    per grouped negotiation (skipped single-process)."""
    import zlib

    k = len(shapes)
    n_header = basics.size()
    if n_header > 1:
        hdr = np.asarray([[k]], np.int32)
        hg = jax.make_array_from_process_local_data(
            basics.rank_sharding(), hdr)
        hh = allgather_async(
            hg, name=None if name is None else f"{name}.shapes.k")
        ks = np.asarray(jax.device_get(synchronize(hh))).reshape(n_header)
        for r in range(n_header):
            if int(ks[r]) != k:
                raise ValueError(
                    f"allgather: group member count differs on rank {r}: "
                    f"rank {r} negotiates {int(ks[r])} member(s) vs "
                    f"local {k} — every rank must pass the same-length "
                    f"tensor list to a grouped allgather")
    digest = np.zeros((k, 2 + MAX_GATHER_NDIM), np.int32)
    crcs = []
    for i, (shape, dtype_str) in enumerate(zip(shapes, dtype_strs)):
        ndim = len(shape)
        if ndim < 1:
            raise ValueError("allgather expects a tensor with >= 1 dim")
        if ndim > MAX_GATHER_NDIM:
            raise ValueError(
                f"allgather supports up to {MAX_GATHER_NDIM} dims, "
                f"got {ndim}"
            )
        # int32 end-to-end: jax's default x64-truncation would silently
        # fold int64 digests and break the cross-rank comparison.  Dims
        # that don't fit int32 would wrap silently, so reject up front.
        if any(d > 0x7FFFFFFF for d in shape):
            raise ValueError(
                "allgather: tensor dims must fit in int32 for the "
                f"cross-rank shape negotiation; got shape {tuple(shape)}"
            )
        digest[i, 0] = ndim
        # crc32, not hash(): Python's str hash is per-process randomized.
        crc = zlib.crc32(dtype_str.encode()) & 0x7FFFFFFF
        crcs.append(crc)
        digest[i, 1] = crc
        digest[i, 2:2 + ndim] = list(shape)
    n = basics.size()
    flat = digest.reshape(1, -1)
    if n == 1:
        g = jax.device_put(flat, basics.rank_sharding())
    else:
        g = jax.make_array_from_process_local_data(
            basics.rank_sharding(), flat
        )
    h = allgather_async(g, name=None if name is None else f"{name}.shapes")
    all_digest = np.asarray(
        jax.device_get(synchronize(h))
    ).reshape(n, k, 2 + MAX_GATHER_NDIM)
    out: list[list[int]] = []
    for i, shape in enumerate(shapes):
        ndim = len(shape)
        member = f" (group member {i})" if k > 1 else ""
        for r in range(n):
            if (all_digest[r, i, 0] != ndim
                    or all_digest[r, i, 1] != crcs[i]):
                raise ValueError(
                    "allgather: per-rank tensors must share ndim and "
                    f"dtype; rank {r} disagrees{member} "
                    f"({all_digest[r, i, :2].tolist()} vs "
                    f"{[ndim, crcs[i]]})"
                )
            if list(all_digest[r, i, 3:2 + ndim]) != list(shape[1:]):
                raise ValueError(
                    "allgather: per-rank tensors must agree on all dims "
                    f"except dim 0; rank {r} has trailing{member} "
                    f"{all_digest[r, i, 3:2 + ndim].tolist()} vs local "
                    f"{list(shape[1:])}"
                )
        out.append([int(all_digest[r, i, 2]) for r in range(n)])
    return out


def negotiate_alltoall_splits(splits: Sequence[int], dim0: int,
                              name: str | None = None) -> np.ndarray:
    """Exchange per-rank alltoall split rows THROUGH the engine (so the
    negotiation serializes with every queued op, like
    :func:`negotiate_gather_sizes`) and return the full [n, n] matrix —
    ``S[r, j]`` = rows rank r sends to rank j.  Every rank derives the
    same padding (``S.max()``) and its own receive column from it.

    Validation that depends on a rank's OWN values (row length,
    negativity, sum == its dim 0) happens AFTER the exchange, against
    the gathered matrix, so a bad rank raises the same error on every
    rank instead of deadlocking the others in the negotiation (the
    :func:`negotiate_gather_sizes` discipline)."""
    n = basics.size()
    row = np.asarray(list(splits), np.int64)
    if row.shape != (n,):
        # A wrong-LENGTH row can't be exchanged at the fixed wire shape
        # at all — this is a local programming error, same on any rank
        # that makes it.
        raise ValueError(
            f"alltoall splits must have one entry per rank "
            f"({n}), got shape {row.shape}")
    rec = np.concatenate([
        np.clip(row, -0x80000000, 0x7FFFFFFF),
        [min(dim0, 0x7FFFFFFF)],
    ]).astype(np.int32)[None]
    if n == 1:
        g = jax.device_put(rec, basics.rank_sharding())
    else:
        g = jax.make_array_from_process_local_data(
            basics.rank_sharding(), rec)
    h = allgather_async(g, name=None if name is None else f"{name}.splits")
    allrec = np.asarray(
        jax.device_get(synchronize(h))).reshape(n, n + 1)
    mat, dims = allrec[:, :n].astype(np.int64), allrec[:, n]
    for r in range(n):
        if (mat[r] < 0).any():
            raise ValueError(
                f"alltoall splits must be non-negative; rank {r} sent "
                f"{mat[r].tolist()}")
        if mat[r].sum() != dims[r]:
            raise ValueError(
                f"alltoall splits sum {int(mat[r].sum())} != tensor "
                f"dim 0 {int(dims[r])} on rank {r}")
    return mat.astype(np.int32)


def alltoall_async(tensor, name: str | None = None) -> int:
    """Async all-to-all (the hvd.alltoall API Horovod grew in 0.20, equal
    splits): rank r's row of the rank-major input is split into ``size``
    chunks; its output row is chunk r from every rank.  The result is
    RANK-MAJOR ``[size, m, ...]`` — per-rank values differ by design."""
    eng = _engine()
    t = _as_rank_major(tensor, "alltoall")
    n = basics.size()
    if t.ndim < 2 or t.shape[1] % n != 0:
        # Report the PER-RANK shape: callers (esp. the torch surface)
        # passed a per-rank tensor and never saw the rank-major wrapper.
        raise ValueError(
            "alltoall expects each rank's dim 0 to be divisible by "
            f"size={n}; got per-rank shape {t.shape[1:]}"
        )
    name = name or _auto_name("alltoall")
    h = eng.handles.allocate(name)
    eng.enqueue(
        _PendingOp(kind="alltoall", handle=h, tensor=t, name=name)
    )
    return h


def alltoall(tensor, name: str | None = None):
    return synchronize(alltoall_async(tensor, name))


def barrier(name: str | None = None) -> None:
    """Process-level barrier (the hvd.barrier API Horovod grew in 0.23):
    returns only after every rank has entered it.  Implemented as a
    1-element Sum allreduce drained through the engine, so it also
    serializes with every eager op enqueued before it — reaching the
    barrier means every prior collective on every rank has been matched
    and dispatched."""
    n = basics.size()
    # this process contributes one row per mesh device it owns: [1, 1]
    # in the one-process-per-chip world, [n, 1] single-controller
    mine = sum(1 for d in basics.mesh().devices.flat
               if d.process_index == jax.process_index())
    rows = np.ones((mine, 1), np.float32)
    if mine == n:
        g = jax.device_put(rows, basics.rank_sharding())
    else:
        g = jax.make_array_from_process_local_data(
            basics.rank_sharding(), rows)
    out = synchronize(allreduce_async(
        g, op=Sum, name=name or _auto_name("barrier"),
        # a rendezvous must not be satisfiable by a joined rank's zero
        # phantom (hvd.join would quietly turn the barrier into n-1
        # arrivals); OP_OTHER classification makes the controller error
        # it cleanly instead.  no_fuse keeps its dispatch self-contained.
        no_fuse=True, join_identity=False))
    total = float(np.asarray(jax.device_get(out))[0])
    if total != float(n):          # engine invariant, not user error
        raise HorovodInternalError(
            f"barrier saw contribution sum {total} != world size {n}")


def reducescatter_async(tensor, name: str | None = None, *,
                        op: _ReduceOp = Average) -> int:
    """Async reduce-scatter (the hvd.reducescatter API Horovod grew in
    0.21): the rank-major input is reduced with ``op`` (Sum/Average —
    default Average, matching Horovod's signature) and each rank keeps
    shard r of the result along dim 0.  The result is RANK-MAJOR
    ``[size, m/size, ...]`` — per-rank shards differ by design.  Dim 0 of
    each rank's tensor must be divisible by ``size`` (equal shards, like
    ``alltoall``)."""
    eng = _engine()
    t = _as_rank_major(tensor, "reducescatter")
    n = basics.size()
    if op not in (Sum, Average):
        raise ValueError(f"reducescatter supports Sum/Average, not {op}")
    if t.ndim < 2 or t.shape[1] % n != 0:
        raise ValueError(
            "reducescatter expects each rank's dim 0 to be divisible by "
            f"size={n}; got per-rank shape {t.shape[1:]}"
        )
    name = name or _auto_name("reducescatter")
    h = eng.handles.allocate(name)
    eng.enqueue(
        _PendingOp(kind="reducescatter", handle=h, tensor=t, name=name,
                   op=op)
    )
    return h


def reducescatter(tensor, name: str | None = None, *,
                  op: _ReduceOp = Average):
    return synchronize(reducescatter_async(tensor, name, op=op))


def join() -> int:
    """``hvd.join()`` (Horovod ≥0.21): this rank is out of data — block
    until every rank joins, contributing zeros to the gang's remaining
    plain Sum/Average allreduces meanwhile.  Returns the last rank to
    join.  See ``EagerEngine.join`` for the mechanics."""
    return _engine().join()


def broadcast_async(tensor, root_rank: int, name: str | None = None, *,
                    process_set=None) -> int:
    """Async broadcast of rank ``root_rank``'s slice to all
    (reference horovod/torch/mpi_ops.py:318-405).  With a ``process_set``
    the output is rank-major: members carry the root's value, non-members
    their own input."""
    eng = _engine()
    t = _as_rank_major(tensor, "broadcast")
    if not 0 <= root_rank < basics.size():
        raise ValueError(f"root_rank {root_rank} outside [0, {basics.size()})")
    if process_set is not None and not process_set.included(root_rank):
        raise ValueError(
            f"broadcast root_rank {root_rank} is not in {process_set!r}"
        )
    name = name or _auto_name("broadcast")
    h = eng.handles.allocate(name)
    eng.enqueue(
        _PendingOp(
            kind="broadcast",
            handle=h,
            tensor=t,
            name=name,
            root_rank=root_rank,
            process_set=process_set,
        )
    )
    return h


def broadcast(tensor, root_rank: int, name: str | None = None, *,
              process_set=None):
    return synchronize(broadcast_async(tensor, root_rank, name,
                                       process_set=process_set))


def poll(handle: int) -> bool:
    """Non-blocking completion probe (reference torch/mpi_ops.py:406-419)."""
    eng = _engine()
    eng.flush()
    return eng.handles.poll(handle)


def engine_stats() -> dict:
    """Snapshot of the engine's observability counters.

    Keys: ``ops_enqueued``, ``batches_dispatched`` (one compiled collective
    launch each), ``tensors_fused`` (ops that rode a multi-tensor fused
    bucket — the Tensor Fusion win meter), ``allreduce_bytes`` (per-rank
    payload), ``errors`` (failed handles, dispatch or negotiation),
    ``stall_warnings`` (stall-checker firings).
    Values are monotonic since ``init()``; before the engine's first eager
    op this reports ``{}``.  A snapshot, not a barrier: in-flight ops may
    not be counted yet.  ``recent_negotiate_s`` is the last-N negotiate
    waits (enqueue → dispatch, seconds) — the straggler detector's
    rolling-window feed.
    """
    eng = basics._state.engine
    if eng is None:
        return {}
    out: dict = dict(eng.stats)
    out["recent_negotiate_s"] = list(eng.recent_negotiate_s)
    return out


def take_handle_post(handle: int):
    """Detach the handle's post payload; None if absent/released."""
    return _engine().handles.take_post(handle)


def update_handle_post(handle: int, **items) -> None:
    """Merge keys into a dict post payload, atomically under the manager
    lock."""
    _engine().handles.update_post(handle, items)


def release(handle: int) -> None:
    """Drop a handle without waiting — frees its manager entry (and any
    post payload).  No-op if already released.  For error-path cleanup
    where blocking on the result is pointless."""
    _engine().handles.release(handle)


def synchronize(handle: int):
    """Block until the op completes; returns its output
    (reference torch/mpi_ops.py:422-438)."""
    eng = _engine()
    if eng.timeline is not None:
        tname = eng.handles.name(handle)
        if tname is not None:
            # Flush BEFORE opening the span so this tensor's own
            # NEGOTIATE-end / DISPATCH / op events precede it; the span is
            # an async event (matched by handle id, not the B/E stack), so
            # a concurrent cycle-thread dispatch cannot mis-nest it either.
            eng.flush()
            eng.timeline.async_start(
                tname, timeline_mod.WAIT_FOR_OUTPUT, handle
            )
            try:
                return eng.handles.wait(handle, lambda: None)
            finally:
                eng.timeline.async_end(
                    tname, timeline_mod.WAIT_FOR_OUTPUT, handle
                )
    return eng.handles.wait(handle, eng.flush)


def grouped_allreduce_eager(
    tensors: Sequence, average: bool | None = None, names: list[str] | None = None,
    *, op: _ReduceOp = Sum, compression=Compression.none,
) -> list:
    """Enqueue many allreduces in one call; the engine fuses them into
    buckets (the reference achieves this implicitly when many grads arrive in
    one cycle — test/test_torch.py:175-224 ``..._async_fused``).

    The call delimits a fusion group: members enter the engine queue
    atomically and, under Python-planned fusion (single host or
    controller-less multi-host), fuse only with each other
    (``EagerEngine._fuse_key``) — bucket composition and the compiled
    dispatch-program signatures are then deterministic for a given call
    shape, across hosts AND across repeated calls (no cycle-tick-dependent
    compile churn).  The native-controller path instead merges by
    negotiated fusability (globally consistent, timing-dependent —
    docs/tensor-fusion.md "Determinism and compile churn")."""
    if names is not None and len(names) != len(tensors):
        raise ValueError(
            f"names has {len(names)} entries for {len(tensors)} tensors"
        )
    gid = next(_group_counter)
    eng = None
    pendings = []
    for i, t in enumerate(tensors):
        eng, p = _prepare_allreduce(
            t, average, (names[i] if names else None),
            op=op, compression=compression, group_id=gid,
            process_set=None, no_fuse=False,
        )
        pendings.append(p)
    if eng is not None:
        eng.enqueue_many(pendings)
    return [synchronize(p.handle) for p in pendings]
