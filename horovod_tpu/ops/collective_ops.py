"""In-graph collective ops — the SPMD data plane.

TPU-native replacement for the reference's MPI/NCCL data plane
(reference: horovod/common/operations.cc:734-1420 ``PerformOperation``).
Where the reference memcpys tensors into a fusion buffer and calls
``ncclAllReduce`` / ``MPI_Allreduce`` on a background thread, the TPU data
plane is **compiled**: these functions are called *inside* ``shard_map`` /
``pjit`` over a device mesh, and XLA emits the matching ICI/DCN collective
(all-reduce, all-gather, collective-permute, all-to-all, reduce-scatter).

Fusion, scheduling, and stream management all belong to XLA here; what this
module owns is the *semantics* (op types, averaging, compression hooks) and
the Horovod-shaped API.

All functions take ``axis_name`` (default ``"hvd"``) so they compose with any
user mesh — e.g. ``axis_name="data"`` in a (data, model) 2-D mesh, or a tuple
``("ici", "dcn")`` which is the TPU-native form of the reference's
hierarchical allreduce (operations.cc:1070-1223): XLA performs the reduction
over fast ICI within a slice and DCN across slices from the same program.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.basics import AXIS_NAME
from horovod_tpu.ops.compression import Compression, Compressor


class _ReduceOp:
    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:  # pragma: no cover
        return f"horovod_tpu.{self.name}"


# Reduction op vocabulary.  The reference only ships SUM (with client-side
# divide for average — horovod/tensorflow/__init__.py:45-87); Min/Max/Product
# are included because lax provides them for free on TPU; Adasum is the
# scaled-sensitivity combination the Horovod project added in 0.20 (here a
# ppermute butterfly — see adasum_allreduce).
Sum = _ReduceOp("Sum")
Average = _ReduceOp("Average")
Min = _ReduceOp("Min")
Max = _ReduceOp("Max")
Product = _ReduceOp("Product")
Adasum = _ReduceOp("Adasum")


def _axis_size(axis_name) -> jax.Array | int:
    if isinstance(axis_name, (tuple, list)):
        out = 1
        for a in axis_name:
            out = out * lax.axis_size(a)
        return out
    return lax.axis_size(axis_name)


def _reduce(x: jax.Array, op: _ReduceOp, axis_name) -> jax.Array:
    if op is Sum:
        return lax.psum(x, axis_name)
    if op is Average:
        return lax.pmean(x, axis_name)
    if op is Min:
        return lax.pmin(x, axis_name)
    if op is Max:
        return lax.pmax(x, axis_name)
    if op is Product:
        return _pprod(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def _pprod(x: jax.Array, axis_name) -> jax.Array:
    """Product reduction without a pprod primitive, in O(1) extra memory.

    An ``all_gather`` + ``prod`` would materialize world_size copies of the
    tensor per device (1 GiB × 64 ranks = 64 GiB); instead exchange-and-
    multiply keeps exactly one extra buffer in flight: a recursive-doubling
    butterfly (log₂ n ``ppermute`` rounds, partner at distance 2ⁱ) for
    power-of-two axes, a ring (n-1 shift-by-one rounds) otherwise.  Exact
    for ints; floats reassociate like any tree reduction.  Tuple axes
    reduce one axis at a time — multiplication commutes, so the product
    over (a, b) is the product over a of the product over b.
    """
    if isinstance(axis_name, (tuple, list)):
        for a in axis_name:
            x = _pprod(x, a)
        return x
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    if n & (n - 1) == 0:
        for i in range(n.bit_length() - 1):
            d = 1 << i
            perm = [(r, r ^ d) for r in range(n)]
            x = x * lax.ppermute(x, axis_name, perm)
        return x
    perm = [(r, (r + 1) % n) for r in range(n)]
    out, cur = x, x
    for _ in range(n - 1):
        cur = lax.ppermute(cur, axis_name, perm)
        out = out * cur
    return out


class ProcessSet:
    """A static subset of ranks that collectives can run over — the API the
    Horovod project added in 0.22 (``hvd.ProcessSet``), TPU-native.

    Where Horovod builds a sub-communicator per set, XLA collectives take
    ``axis_index_groups``: a ProcessSet lowers to a partition of the mesh
    axis into [the member group] + singleton groups for everyone else, so
    members reduce together and non-members pass through unchanged —
    no communicator state, no registration step, works inside any
    compiled program.

    Under SPMD every rank executes the same program, so "non-members don't
    call the op" (Horovod's model) becomes "non-members run the identity";
    results on non-member ranks are their own inputs.
    """

    def __init__(self, ranks):
        rs = sorted(int(r) for r in ranks)
        if len(rs) != len(set(rs)):
            raise ValueError(f"duplicate ranks in process set: {ranks}")
        if not rs:
            raise ValueError("a process set needs at least one rank")
        if rs[0] < 0:
            raise ValueError(f"negative rank in process set: {ranks}")
        self.ranks = tuple(rs)

    def __repr__(self) -> str:  # pragma: no cover
        return f"ProcessSet{self.ranks}"

    def size(self) -> int:
        return len(self.ranks)

    def rank_of(self, global_rank: int) -> int:
        """Set-local rank of ``global_rank``, or -1 if not a member."""
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            return -1

    def included(self, global_rank: int) -> bool:
        return global_rank in self.ranks

    def groups(self, world_size: int) -> list[list[int]]:
        """The axis_index_groups partition: members together, everyone
        else alone."""
        if self.ranks[-1] >= world_size:
            raise ValueError(
                f"process set {self.ranks} exceeds world size {world_size}"
            )
        member = set(self.ranks)
        return [list(self.ranks)] + [
            [r] for r in range(world_size) if r not in member
        ]

    def groups_for_axis(self, axis_name) -> list[list[int]]:
        """``groups()`` for a traced mesh axis — the one place that
        rejects tuple axes (axis_index_groups needs a single axis) and
        bounds-checks the ranks against the axis size."""
        if isinstance(axis_name, (tuple, list)):
            raise ValueError(
                "process_set collectives need a single mesh axis; flatten "
                "the hierarchical axes first"
            )
        return self.groups(lax.axis_size(axis_name))

    def member_mask(self, axis_name) -> jax.Array:
        """Traced predicate: is the executing rank a member?"""
        idx = lax.axis_index(axis_name)
        return jnp.any(idx == jnp.asarray(self.ranks))


def _adasum_pair(a: jax.Array, b: jax.Array) -> jax.Array:
    """The Adasum combination of two flat fp32 gradients:

        adasum(a, b) = (1 − a·b / 2‖a‖²)·a + (1 − a·b / 2‖b‖²)·b

    When a ⊥ b this is a+b (independent descent directions add); when
    a ∥ b it is their average (redundant directions don't double-step).
    A zero operand degrades to returning the other (the max() guard makes
    its coefficient 1 and its own term 0)."""
    dot = jnp.vdot(a, b)
    na2 = jnp.vdot(a, a)
    nb2 = jnp.vdot(b, b)
    tiny = jnp.asarray(1e-30, a.dtype)
    ca = 1.0 - dot / jnp.maximum(2.0 * na2, tiny)
    cb = 1.0 - dot / jnp.maximum(2.0 * nb2, tiny)
    return ca * a + cb * b


def adasum_allreduce(
    tensor: jax.Array,
    *,
    axis_name=AXIS_NAME,
) -> jax.Array:
    """Adasum reduction over the mesh axis (Horovod ≥0.20 capability).

    Power-of-two worlds run the recursive-doubling **butterfly**: log₂(n)
    ``ppermute`` exchange rounds, each rank combining with its partner at
    distance 2ⁱ — the combination is symmetric, so partners stay identical
    and the result is replicated with n·log₂(n) total wire instead of a
    gather.  Other world sizes (and tuple axes) all-gather and reduce the
    same fixed pairwise tree locally (deterministic and rank-identical by
    construction).  Dot products and norms are taken over THIS tensor
    only, which is why Adasum ops never join fusion buckets — a fused
    buffer would mix unrelated layers into one inner product.

    Wire dtype: the tensor's own floating dtype (a 16-bit tensor from a
    cast compressor moves 16-bit words on every exchange); arithmetic is
    fp32.  Rank-symmetry is preserved by combining the quantized copy of
    SELF with the quantized copy of the partner — both sides then compute
    on identical operands, so the result stays replicated.
    """
    n = _axis_size(axis_name)
    if n == 1:
        return tensor
    orig_dtype = tensor.dtype
    wire_dtype = (
        orig_dtype if jnp.issubdtype(orig_dtype, jnp.floating)
        else jnp.float32
    )
    v = tensor.reshape(-1).astype(jnp.float32)
    if n & (n - 1) == 0 and not isinstance(axis_name, (tuple, list)):
        for i in range(n.bit_length() - 1):
            d = 1 << i
            perm = [(r, r ^ d) for r in range(n)]
            send = v.astype(wire_dtype)
            pv = lax.ppermute(send, axis_name, perm)
            v = _adasum_pair(
                send.astype(jnp.float32), pv.astype(jnp.float32)
            )
    else:
        vs = lax.all_gather(v.astype(wire_dtype), axis_name)   # [n, d]
        level = [vs[i].astype(jnp.float32) for i in range(n)]
        while len(level) > 1:
            nxt = [
                _adasum_pair(level[2 * j], level[2 * j + 1])
                for j in range(len(level) // 2)
            ]
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
        v = level[0]
    return v.reshape(tensor.shape).astype(orig_dtype)


def _process_set_allreduce(
    tensor: jax.Array,
    ps: ProcessSet,
    op: _ReduceOp,
    axis_name,
    compression: Compressor,
) -> jax.Array:
    """Members reduce together (one axis_index_groups collective);
    non-members receive their input unchanged."""
    if op not in (Sum, Average, Min, Max):
        raise ValueError(f"process_set supports Sum/Average/Min/Max, not {op}")
    groups = ps.groups_for_axis(axis_name)
    compressed, ctx = compression.compress(tensor)
    if op in (Min, Max):
        fn = lax.pmin if op is Min else lax.pmax
        red = fn(compressed, axis_name, axis_index_groups=groups)
    else:
        red = lax.psum(compressed, axis_name, axis_index_groups=groups)
        if op is Average:
            # Non-members' singleton psum is their own value; dividing it
            # would corrupt the pass-through, so the divide is member-only.
            red = jnp.where(
                ps.member_mask(axis_name), red / ps.size(), compressed
            )
    return compression.decompress(red, ctx)


def allreduce(
    tensor: jax.Array,
    average: bool | None = None,
    *,
    op: _ReduceOp = Sum,
    axis_name=AXIS_NAME,
    compression: Compressor = Compression.none,
    process_set: ProcessSet | None = None,
) -> jax.Array:
    """All-reduce ``tensor`` over ``axis_name``.

    Semantics of the reference's ``hvd.allreduce``
    (horovod/tensorflow/__init__.py:45-87): optional compression around the
    wire transfer, optional divide-by-size.  On TPU the "wire" is an XLA
    all-reduce over ICI — one fused HLO, no fusion-buffer memcpys.

    ``average=True`` matches the reference's default-flag API; ``op=`` is the
    forward-looking spelling.  Gradients: all-reduce is self-adjoint, and
    ``lax.psum`` already differentiates to ``psum`` — the hand-registered
    gradient of the reference (horovod/tensorflow/mpi_ops.py:93-104) is
    automatic here.

    ``process_set`` restricts the reduction to a rank subset (Horovod
    ≥0.22 API); non-member ranks receive their input unchanged.
    """
    if average is not None:
        op = Average if average else Sum
    if process_set is not None:
        if op is Adasum or callable(
            getattr(compression, "quantized_allreduce", None)
        ):
            raise ValueError(
                "process_set does not compose with Adasum or wire-format "
                "compressors; use Sum/Average/Min/Max with none/fp16/bf16"
            )
        return _process_set_allreduce(
            tensor, process_set, op, axis_name, compression
        )
    if op in (Min, Max, Product):
        return _reduce(tensor, op, axis_name)
    if op is Adasum:
        if callable(getattr(compression, "quantized_allreduce", None)):
            raise ValueError(
                "Adasum does not support wire-format compressors (int8): "
                "the combination needs full vectors on every exchange. "
                "Use Compression.fp16/bf16 — Adasum then moves 16-bit "
                "words on the wire."
            )
        compressed, ctx = compression.compress(tensor)
        reduced = adasum_allreduce(compressed, axis_name=axis_name)
        return compression.decompress(reduced, ctx)
    quantized = getattr(compression, "quantized_allreduce", None)
    if callable(quantized):
        # Wire-format compressors (int8) replace the collective itself:
        # quantized all_gather + local dequant-sum instead of psum.
        return quantized(tensor, average=op is Average, axis_name=axis_name)
    compressed, ctx = compression.compress(tensor)
    reduced = _reduce(compressed, op, axis_name)
    return compression.decompress(reduced, ctx)


def grouped_allreduce(
    tensors: Sequence[jax.Array],
    average: bool | None = None,
    *,
    op: _ReduceOp = Sum,
    axis_name=AXIS_NAME,
    compression: Compressor = Compression.none,
    fusion_threshold_bytes: int | None = None,
    process_set: ProcessSet | None = None,
) -> list[jax.Array]:
    """All-reduce many tensors as few fused transfers — Tensor Fusion.

    The reference fuses by memcpying tensors into a 64 MiB buffer and issuing
    one collective (operations.cc:999-1053, 1916-1943).  The TPU-native form
    flattens and concatenates same-dtype tensors into buckets of at most
    ``fusion_threshold_bytes`` and issues one ``psum`` per bucket; XLA further
    combines adjacent collectives.  See :mod:`horovod_tpu.ops.fusion`.
    """
    from horovod_tpu.ops import fusion

    if average is not None:
        op = Average if average else Sum
    if op is Adasum:
        # Adasum's dot products are per-tensor; a fused buffer would mix
        # unrelated layers into one inner product.  One collective each.
        fusion_threshold_bytes = 0
    return fusion.fused_apply(
        list(tensors),
        lambda flat: allreduce(
            flat, op=op, axis_name=axis_name, compression=compression,
            process_set=process_set,
        ),
        threshold_bytes=fusion_threshold_bytes,
    )


def allgather(
    tensor: jax.Array,
    *,
    axis_name=AXIS_NAME,
) -> jax.Array:
    """Concatenate every rank's ``tensor`` along axis 0.

    Semantics of the reference's allgather (tensorflow/mpi_ops.cc:334-391):
    ranks may disagree on dim 0 but must agree on other dims.  Inside a
    compiled SPMD program shapes are static and equal per rank, so this is
    exactly ``lax.all_gather(tiled=True)``; the ragged case is an eager-path
    feature (see :func:`horovod_tpu.ops.eager.allgather`, which negotiates
    sizes host-side the way the coordinator negotiates shapes in
    operations.cc:841-901).
    """
    return lax.all_gather(tensor, axis_name, tiled=True)


def broadcast(
    tensor: jax.Array,
    root_rank: int,
    *,
    axis_name=AXIS_NAME,
    process_set: ProcessSet | None = None,
) -> jax.Array:
    """Every rank receives ``root_rank``'s value of ``tensor``.

    Reference semantics: tensorflow/mpi_ops.cc:393-463.  Lowered as a
    masked ``psum`` — ``where(rank == root, x, 0)`` then ONE all-reduce.

    Wire cost, honestly stated: a ring all-reduce moves ``2(n-1)/n ×
    bytes`` per ICI link — a constant ≤2× over the optimal pipelined ring
    broadcast's ``(n-1)/n × bytes``, INDEPENDENT of n.  This is the
    deliberate TPU-first choice over the reference's MPI tree bcast
    (operations.cc:1403-1407): the alternatives expressible in XLA today
    are strictly worse at scale — a one-to-many ``collective-permute``
    concentrates ``(n-1) × bytes`` on the root's own links (linear in n),
    and ``all_gather``+index materializes and moves ``n ×`` the tensor.
    XLA may further simplify the masked all-reduce; we do not rely on it.
    The single-collective shape (no gather blowup, no one-to-many permute)
    is pinned by ``tests/test_spmd_ops.py::test_broadcast_lowering``.
    Works for every dtype (bool/int via bitcast-free select on zeros).

    With ``process_set``, ``root_rank`` must be a member; member ranks
    receive the root's value, non-members their own input.
    """
    if process_set is not None and not process_set.included(root_rank):
        raise ValueError(
            f"broadcast root_rank {root_rank} is not in {process_set!r}"
        )
    # lax.axis_index natively combines tuple axes row-major, so the
    # hierarchical (dcn, ici) form needs no special case: ranks follow the
    # mesh's device order.
    idx = lax.axis_index(axis_name)
    mask = idx == root_rank
    groups = None
    if process_set is not None:
        groups = process_set.groups_for_axis(axis_name)
    wire = tensor
    is_bool = jnp.issubdtype(tensor.dtype, jnp.bool_)
    if is_bool:
        wire = tensor.astype(jnp.int8)
    masked = jnp.where(mask, wire, jnp.zeros_like(wire))
    out = lax.psum(masked, axis_name, axis_index_groups=groups)
    if process_set is not None:
        # Non-members' singleton psum yields 0 (they are not the root);
        # restore their own input.
        out = jnp.where(process_set.member_mask(axis_name), out, wire)
    if is_bool:
        return out.astype(jnp.bool_)
    return out


def alltoall(
    tensor: jax.Array,
    *,
    split_axis: int = 0,
    concat_axis: int = 0,
    axis_name=AXIS_NAME,
) -> jax.Array:
    """All-to-all exchange (no reference equivalent; the TPU-native primitive
    backing sequence-parallel attention — see horovod_tpu.parallel)."""
    return lax.all_to_all(
        tensor, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def reducescatter(
    tensor: jax.Array,
    *,
    op: _ReduceOp = Sum,
    axis_name=AXIS_NAME,
) -> jax.Array:
    """Reduce-scatter: each rank gets one reduced shard (axis 0 tiled).

    The reference uses this internally as the first leg of hierarchical
    allreduce (ncclReduceScatter, operations.cc:1135-1158); on TPU it is a
    first-class op (``lax.psum_scatter``) and the building block of
    ZeRO-style sharded optimizers.
    """
    out = lax.psum_scatter(tensor, axis_name, tiled=True)
    if op is Average:
        return out / _axis_size(axis_name)
    if op is not Sum:
        raise ValueError("reducescatter supports Sum / Average")
    return out


def barrier(*, axis_name=AXIS_NAME,
            process_set: ProcessSet | None = None) -> None:
    """Synchronization barrier — a 1-element psum every rank must join
    (members only, when a ``process_set`` is given)."""
    groups = (
        process_set.groups_for_axis(axis_name)
        if process_set is not None else None
    )
    lax.psum(jnp.ones((), jnp.int32), axis_name, axis_index_groups=groups)
